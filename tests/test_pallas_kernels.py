"""Pallas kernel correctness vs the dense reference path.

Kernels run in interpret mode (CPU); the dense jnp implementations in
``gofr_tpu.ops.attention`` are the oracle. Mirrors the reference's
fake-backend test idiom (SURVEY §4: miniredis stands in for Redis; here the
interpreter stands in for the TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gofr_tpu.ops.attention import attention, decode_attention
from gofr_tpu.ops.pallas import flash_attention, flash_decode


def _qkv(key, b, s_q, s_kv, n_heads, n_kv, hd, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s_q, n_heads, hd), dtype)
    k = jax.random.normal(kk, (b, s_kv, n_kv, hd), dtype)
    v = jax.random.normal(kv, (b, s_kv, n_kv, hd), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "b,s_q,s_kv,n_heads,n_kv,hd,causal",
    [
        (1, 64, 64, 4, 4, 32, True),     # MHA causal
        (2, 64, 64, 4, 2, 32, True),     # GQA
        (1, 32, 128, 4, 2, 32, True),    # query is suffix of keys
        (2, 64, 64, 4, 2, 32, False),    # non-causal (encoder)
        (1, 50, 70, 4, 2, 32, True),     # ragged: padding both axes
    ],
)
def test_flash_attention_matches_dense(b, s_q, s_kv, n_heads, n_kv, hd, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0), b, s_q, s_kv, n_heads, n_kv, hd)
    want = attention(q, k, v, causal=causal)
    got = flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,s,lengths,causal",
    [
        (3, 64, [1, 33, 64], True),     # ragged right-padded rows, causal
        (2, 128, [100, 17], True),      # lengths off block boundaries
        (2, 64, [40, 64], False),       # non-causal (encoder-style)
    ],
)
def test_flash_attention_lengths_matches_dense(b, s, lengths, causal):
    """The serving-prefill case: per-row valid prefixes masked in-kernel
    (VERDICT r1 weak #3 — prefill must keep the kernel path)."""
    q, k, v = _qkv(jax.random.PRNGKey(3), b, s, s, 4, 2, 32)
    lens = jnp.asarray(lengths, dtype=jnp.int32)
    want = attention(q, k, v, causal=causal, lengths=lens, kernel=False)
    got = flash_attention(
        q, k, v, lens, causal=causal, block_q=32, block_k=32, interpret=True
    )
    # Rows at/after a row's own length are padding queries — the kernel
    # emits 0 there while the dense path emits uniform-softmax junk; only
    # compare valid rows.
    for i, ln in enumerate(lengths):
        np.testing.assert_allclose(
            np.asarray(got)[i, :ln], np.asarray(want)[i, :ln],
            atol=2e-5, rtol=2e-5,
        )


def test_attention_lengths_dispatches_kernel(monkeypatch):
    """attention(lengths=...) must keep the kernel path when flash is on."""
    import importlib

    # `import gofr_tpu.ops.attention as m` would bind the re-exported
    # FUNCTION (ops/__init__ shadows the submodule name); go via sys.modules.
    attn_mod = importlib.import_module("gofr_tpu.ops.attention")

    called = {}
    real = flash_attention

    def spy(q, k, v, lengths=None, **kw):
        called["lengths"] = lengths
        return real(q, k, v, lengths, **kw)

    monkeypatch.setattr(attn_mod, "_flash_enabled", lambda: True)
    monkeypatch.setattr(attn_mod, "_interpret", lambda: True)
    import gofr_tpu.ops.pallas as pallas_pkg

    monkeypatch.setattr(pallas_pkg, "flash_attention", spy)
    q, k, v = _qkv(jax.random.PRNGKey(4), 2, 32, 32, 4, 2, 32)
    lens = jnp.asarray([10, 32], dtype=jnp.int32)
    attn_mod.attention(q, k, v, causal=True, lengths=lens)
    assert called["lengths"] is lens


def test_flash_attention_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(1), 2, 64, 64, 4, 2, 64, jnp.bfloat16)
    want = attention(q, k, v, causal=True).astype(jnp.float32)
    got = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True
    ).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize(
    "b,max_len,n_heads,n_kv,hd,lengths",
    [
        (4, 128, 4, 4, 32, [1, 7, 64, 128]),   # MHA, ragged lengths
        (2, 256, 8, 2, 32, [100, 256]),        # GQA
        (3, 96, 4, 2, 32, [5, 96, 33]),        # max_len not block-multiple
    ],
)
def test_flash_decode_matches_dense(b, max_len, n_heads, n_kv, hd, lengths):
    key = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, n_heads, hd))
    k_cache = jax.random.normal(kk, (b, n_kv, max_len, hd))
    v_cache = jax.random.normal(kv, (b, n_kv, max_len, hd))
    lens = jnp.array(lengths, dtype=jnp.int32)

    want = decode_attention(q, k_cache, v_cache, lens)
    got = flash_decode(q, k_cache, v_cache, lens, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,max_len,n_heads,n_kv,hd,lengths",
    [
        (4, 128, 4, 4, 32, [0, 7, 64, 127]),   # incl. empty prefix
        (2, 256, 8, 2, 32, [100, 255]),        # GQA
    ],
)
def test_split_decode_matches_write_then_attend(
    b, max_len, n_heads, n_kv, hd, lengths
):
    """decode_attention(k_new=...) over the cache PREFIX must equal the
    old convention (token written at lengths-1, lengths includes it) —
    dense split vs dense written, and the kernel split path vs dense."""
    key = jax.random.PRNGKey(7)
    kq, kk, kv, kn, vn_key = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, n_heads, hd))
    k_cache = jax.random.normal(kk, (b, n_kv, max_len, hd))
    v_cache = jax.random.normal(kv, (b, n_kv, max_len, hd))
    k_new = jax.random.normal(kn, (b, n_kv, hd))
    v_new = jax.random.normal(vn_key, (b, n_kv, hd))
    prev = jnp.array(lengths, dtype=jnp.int32)

    # Old convention: write the new token at position prev, lengths+1.
    bi = jnp.arange(b)[:, None]
    ki = jnp.arange(n_kv)[None, :]
    kw = k_cache.at[bi, ki, prev[:, None]].set(k_new)
    vw = v_cache.at[bi, ki, prev[:, None]].set(v_new)
    want = decode_attention(kernel=False, q=q, k_cache=kw, v_cache=vw,
                            lengths=prev + 1)

    got = decode_attention(
        q, k_cache, v_cache, prev, k_new=k_new, v_new=v_new, kernel=False
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )

    got_kern = flash_decode(
        q, k_cache, v_cache, prev, k_new=k_new, v_new=v_new, block_k=64,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got_kern), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_split_decode_int8_cache_matches_dense(monkeypatch):
    """The int8-cache + k_new split combination — exactly what int8-KV
    serving runs on TPU — must match the dense split path (kernel in
    interpret mode off-TPU)."""
    from gofr_tpu.ops.kv_cache import quantize_kv

    b, max_len, n_heads, n_kv, hd = 3, 128, 8, 2, 32
    key = jax.random.PRNGKey(11)
    kq, kk, kv, kn, vn_key = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, n_heads, hd), jnp.bfloat16)
    k_f = jax.random.normal(kk, (b, n_kv, max_len, hd))
    v_f = jax.random.normal(kv, (b, n_kv, max_len, hd))
    k_new = jax.random.normal(kn, (b, n_kv, hd), jnp.bfloat16)
    v_new = jax.random.normal(vn_key, (b, n_kv, hd), jnp.bfloat16)
    prev = jnp.array([0, 60, 128], dtype=jnp.int32)

    kq8, ks = quantize_kv(k_f)  # scales [b, n_kv, max_len]
    vq8, vs = quantize_kv(v_f)
    rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
        s[:, :, None, :], (b, n_kv, 8, max_len)
    ).astype(jnp.float32)
    ks8, vs8 = rep8(ks), rep8(vs)

    want = decode_attention(
        q, kq8, vq8, prev, k_new=k_new, v_new=v_new, k_scale=ks8,
        v_scale=vs8, kernel=False,
    ).astype(jnp.float32)
    got = flash_decode(
        q, kq8, vq8, prev, k_new=k_new, v_new=v_new, k_scale=ks8,
        v_scale=vs8, block_k=64, interpret=True,
    ).astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=3e-2, rtol=3e-2
    )


@pytest.mark.parametrize("quant", [False, True])
def test_paged_flash_decode_matches_dense(quant):
    """Table-indexed pool kernel == dense over the gathered view, with a
    scrambled block table, ragged lengths, and the k_new split merge."""
    from gofr_tpu.ops.kv_cache import paged_view, quantize_kv

    b, n_heads, n_kv, hd, bs, mb = 3, 8, 2, 32, 64, 4
    n_blocks = 1 + b * mb
    key = jax.random.PRNGKey(13)
    kp, kv_, kq, kn, vn_k = jax.random.split(key, 5)
    pool_k = jax.random.normal(kp, (n_blocks, n_kv, bs, hd))
    pool_v = jax.random.normal(kv_, (n_blocks, n_kv, bs, hd))
    q = jax.random.normal(kq, (b, n_heads, hd))
    k_new = jax.random.normal(kn, (b, n_kv, hd))
    v_new = jax.random.normal(vn_k, (b, n_kv, hd))
    # Scrambled, non-contiguous table (pool ids 1..12 permuted).
    perm = jax.random.permutation(jax.random.PRNGKey(3), n_blocks - 1) + 1
    table = perm.reshape(b, mb).astype(jnp.int32)
    prev = jnp.array([0, 100, 256], dtype=jnp.int32)

    ks = vs = pks = pvs = None
    if quant:
        pool_k, ksc = quantize_kv(pool_k)  # scales [n_blocks, n_kv, bs]
        pool_v, vsc = quantize_kv(pool_v)
        rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
            s[:, :, None, :], (n_blocks, n_kv, 8, bs)
        ).astype(jnp.float32)
        pks, pvs = rep8(ksc), rep8(vsc)

    vk, vv, vks, vvs = paged_view(table, pool_k, pool_v, jnp.arange(b),
                                  pks, pvs)
    want = decode_attention(
        q, vk, vv, prev, k_new=k_new, v_new=v_new, k_scale=vks,
        v_scale=vvs, kernel=False,
    ).astype(jnp.float32)
    got = flash_decode(
        q, pool_k, pool_v, prev, k_new=k_new, v_new=v_new, k_scale=pks,
        v_scale=pvs, block_table=table, interpret=True,
    ).astype(jnp.float32)
    tol = 3e-2 if quant else 2e-5
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=tol, rtol=tol)


def test_dispatch_and_grad(monkeypatch):
    # Force the kernel path off-TPU (interpret mode) and check both the
    # dispatch and the dense-recompute backward pass.
    import importlib

    att = importlib.import_module("gofr_tpu.ops.attention")
    monkeypatch.setattr(att, "_FLASH_ENV", "1")
    q, k, v = _qkv(jax.random.PRNGKey(3), 1, 32, 32, 4, 2, 32)

    got = att.attention(q, k, v, causal=True)
    want = att.attention(q, k, v, causal=True, kernel=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)

    def loss_kernel(q):
        return jnp.sum(att.attention(q, k, v, causal=True) ** 2)

    def loss_dense(q):
        return jnp.sum(att.attention(q, k, v, causal=True, kernel=False) ** 2)

    g_kernel = jax.grad(loss_kernel)(q)
    g_dense = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(
        np.asarray(g_kernel), np.asarray(g_dense), atol=1e-4, rtol=1e-4
    )


def test_flash_decode_zero_length_slot_is_finite():
    # Empty slots (length 0) must not poison the batch with NaNs.
    b, max_len, n_kv, hd = 2, 64, 2, 32
    q = jnp.ones((b, 4, hd))
    k_cache = jnp.ones((b, n_kv, max_len, hd))
    v_cache = jnp.ones((b, n_kv, max_len, hd))
    lens = jnp.array([0, 10], dtype=jnp.int32)
    got = flash_decode(q, k_cache, v_cache, lens, block_k=64, interpret=True)
    assert bool(jnp.isfinite(got).all())
    np.testing.assert_allclose(np.asarray(got[0]), 0.0)


def test_flash_decode_env_override(monkeypatch):
    """GOFR_TPU_FLASH_DECODE overrides GOFR_TPU_FLASH for decode only —
    the bench's A/B knob for the kernel-vs-fused-dense decode trade."""
    import importlib

    att = importlib.import_module("gofr_tpu.ops.attention")
    monkeypatch.setattr(att, "_FLASH_ENV", "1")
    monkeypatch.setattr(att, "_FLASH_DECODE_ENV", "0")
    assert att._flash_enabled() is True
    assert att._flash_decode_enabled() is False
    monkeypatch.setattr(att, "_FLASH_DECODE_ENV", "1")
    assert att._flash_decode_enabled() is True
    monkeypatch.setattr(att, "_FLASH_DECODE_ENV", "")
    monkeypatch.setattr(att, "_FLASH_ENV", "0")
    assert att._flash_decode_enabled() is False

    # Both paths agree regardless of the knob.
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 32), jnp.float32)
    k_cache = jax.random.normal(jax.random.PRNGKey(1), (2, 2, 64, 32), jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 64, 32), jnp.float32)
    lens = jnp.asarray([5, 64], jnp.int32)
    dense = att.decode_attention(q, k_cache, v_cache, lens, kernel=False)
    kern = att.decode_attention(q, k_cache, v_cache, lens, kernel=True)
    np.testing.assert_allclose(
        np.asarray(kern), np.asarray(dense), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("quant", [False, True])
def test_paged_flash_cache_attention_matches_dense(quant):
    """Table-indexed chunked-prefill kernel == dense over the gathered
    view: scrambled table, ragged starts/lens, GQA, ±int8 scales."""
    from gofr_tpu.ops.attention import cache_chunk_attention
    from gofr_tpu.ops.kv_cache import paged_view, quantize_kv
    from gofr_tpu.ops.pallas import flash_cache_attention

    P, c, n_heads, n_kv, hd, bs, mb = 3, 8, 4, 2, 32, 64, 4
    S = 4
    n_blocks = 1 + S * mb
    key = jax.random.PRNGKey(17)
    kp, kv_, kq = jax.random.split(key, 3)
    pool_k = jax.random.normal(kp, (n_blocks, n_kv, bs, hd))
    pool_v = jax.random.normal(kv_, (n_blocks, n_kv, bs, hd))
    q = jax.random.normal(kq, (P, c, n_heads, hd))
    perm = jax.random.permutation(jax.random.PRNGKey(4), n_blocks - 1) + 1
    table = perm.reshape(S, mb).astype(jnp.int32)
    slots = jnp.array([0, 3, 1], dtype=jnp.int32)
    starts = jnp.array([0, 100, 37], dtype=jnp.int32)
    lens = jnp.array([8, 8, 5], dtype=jnp.int32)

    pks = pvs = None
    if quant:
        pool_k, ksc = quantize_kv(pool_k)
        pool_v, vsc = quantize_kv(pool_v)
        rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
            s[:, :, None, :], (n_blocks, n_kv, 8, bs)
        ).astype(jnp.float32)
        pks, pvs = rep8(ksc), rep8(vsc)

    vk, vv, vks, vvs = paged_view(table, pool_k, pool_v, slots, pks, pvs)
    want = cache_chunk_attention(
        q, vk, vv, jnp.arange(P), starts, lens, k_scale=vks, v_scale=vvs,
        kernel=False,
    ).astype(jnp.float32)
    got = flash_cache_attention(
        q, pool_k, pool_v, slots, starts, lens, k_scale=pks, v_scale=pvs,
        block_table=table, interpret=True,
    ).astype(jnp.float32)
    tol = 3e-2 if quant else 2e-5
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=tol, rtol=tol)


@pytest.mark.parametrize("has_new", [False, True])
def test_windowed_flash_decode_matches_dense(has_new):
    """Sliding-window decode in-kernel == the dense windowed math, both
    calling conventions, ragged lengths crossing the window boundary."""
    b, max_len, n_heads, n_kv, hd, w = 4, 256, 8, 2, 32, 48
    key = jax.random.PRNGKey(21)
    kq, kk, kv_, kn, vn_k = jax.random.split(key, 5)
    q = jax.random.normal(kq, (b, n_heads, hd))
    k_cache = jax.random.normal(kk, (b, n_kv, max_len, hd))
    v_cache = jax.random.normal(kv_, (b, n_kv, max_len, hd))
    lens = jnp.array([1, 40, 100, 255], dtype=jnp.int32)
    kw = {}
    if has_new:
        kw = dict(
            k_new=jax.random.normal(kn, (b, n_kv, hd)),
            v_new=jax.random.normal(vn_k, (b, n_kv, hd)),
        )
    want = decode_attention(
        q, k_cache, v_cache, lens, window=w, kernel=False, **kw
    )
    got = flash_decode(
        q, k_cache, v_cache, lens, window=w, block_k=64, interpret=True,
        **kw,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    # The window must actually bind: full attention differs.
    full = decode_attention(q, k_cache, v_cache, lens, kernel=False, **kw)
    assert not np.allclose(np.asarray(full), np.asarray(want), atol=1e-3)


def test_windowed_paged_flash_decode_matches_dense():
    """Window × paged pool in-kernel == dense over the gathered view —
    the mistral-with-paged-KV serving path stays on the kernel."""
    from gofr_tpu.ops.kv_cache import paged_view

    b, n_heads, n_kv, hd, bs, mb, w = 3, 8, 2, 32, 64, 4, 80
    n_blocks = 1 + b * mb
    key = jax.random.PRNGKey(22)
    kp, kv_, kq, kn, vn_k = jax.random.split(key, 5)
    pool_k = jax.random.normal(kp, (n_blocks, n_kv, bs, hd))
    pool_v = jax.random.normal(kv_, (n_blocks, n_kv, bs, hd))
    q = jax.random.normal(kq, (b, n_heads, hd))
    k_new = jax.random.normal(kn, (b, n_kv, hd))
    v_new = jax.random.normal(vn_k, (b, n_kv, hd))
    perm = jax.random.permutation(jax.random.PRNGKey(5), n_blocks - 1) + 1
    table = perm.reshape(b, mb).astype(jnp.int32)
    prev = jnp.array([0, 100, 250], dtype=jnp.int32)

    vk, vv, _, _ = paged_view(table, pool_k, pool_v, jnp.arange(b))
    want = decode_attention(
        q, vk, vv, prev, k_new=k_new, v_new=v_new, window=w, kernel=False,
    )
    got = flash_decode(
        q, pool_k, pool_v, prev, k_new=k_new, v_new=v_new,
        block_table=table, window=w, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("paged", [False, True])
def test_windowed_flash_cache_attention_matches_dense(paged):
    """Windowed chunked prefill in-kernel == dense windowed math, with
    starts straddling the window boundary (contiguous + paged)."""
    from gofr_tpu.ops.attention import cache_chunk_attention
    from gofr_tpu.ops.kv_cache import paged_view
    from gofr_tpu.ops.pallas import flash_cache_attention

    P, c, n_heads, n_kv, hd, w = 3, 8, 4, 2, 32, 48
    key = jax.random.PRNGKey(23)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (P, c, n_heads, hd))
    slots_arr = jnp.array([0, 3, 1], dtype=jnp.int32)
    starts = jnp.array([0, 100, 37], dtype=jnp.int32)
    lens = jnp.array([8, 8, 5], dtype=jnp.int32)
    if paged:
        S, bs, mb = 4, 64, 4
        n_blocks = 1 + S * mb
        pool_k = jax.random.normal(kk, (n_blocks, n_kv, bs, hd))
        pool_v = jax.random.normal(kv_, (n_blocks, n_kv, bs, hd))
        perm = jax.random.permutation(
            jax.random.PRNGKey(6), n_blocks - 1
        ) + 1
        table = perm.reshape(S, mb).astype(jnp.int32)
        vk, vv, _, _ = paged_view(table, pool_k, pool_v, slots_arr)
        want = cache_chunk_attention(
            q, vk, vv, jnp.arange(P), starts, lens, window=w, kernel=False,
        )
        got = flash_cache_attention(
            q, pool_k, pool_v, slots_arr, starts, lens, block_table=table,
            window=w, interpret=True,
        )
    else:
        S, max_len = 4, 256
        k_cache = jax.random.normal(kk, (S, n_kv, max_len, hd))
        v_cache = jax.random.normal(kv_, (S, n_kv, max_len, hd))
        want = cache_chunk_attention(
            q, k_cache, v_cache, slots_arr, starts, lens, window=w,
            kernel=False,
        )
        got = flash_cache_attention(
            q, k_cache, v_cache, slots_arr, starts, lens, block_k=64,
            window=w, interpret=True,
        )
        # The window must bind for the rows past position w.
        full = cache_chunk_attention(
            q, k_cache, v_cache, slots_arr, starts, lens, kernel=False,
        )
        assert not np.allclose(np.asarray(full), np.asarray(want), atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_windowed_flash_attention_matches_dense():
    """Windowed full-sequence kernel == dense windowed math: suffix
    queries (s_kv > s_q offset), ragged lengths, and the differentiable
    wrapper's dense-recompute backward."""
    from gofr_tpu.ops.attention import attention

    b, s_kv, s_q, n_heads, n_kv, hd, w = 2, 192, 192, 4, 2, 32, 48
    key = jax.random.PRNGKey(31)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s_q, n_heads, hd))
    k = jax.random.normal(kk, (b, s_kv, n_kv, hd))
    v = jax.random.normal(kv_, (b, s_kv, n_kv, hd))

    want = attention(q, k, v, causal=True, window=w, kernel=False)
    got = flash_attention(
        q, k, v, causal=True, window=w, block_q=64, block_k=64,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )
    full = attention(q, k, v, causal=True, kernel=False)
    assert not np.allclose(np.asarray(full), np.asarray(want), atol=1e-3)

    # Suffix-query case: the causal offset composes with the window.
    qs = q[:, -64:]
    want_s = attention(qs, k, v, causal=True, window=w, kernel=False)
    got_s = flash_attention(
        qs, k, v, causal=True, window=w, block_q=64, block_k=64,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got_s), np.asarray(want_s), atol=2e-5, rtol=2e-5
    )

    # Ragged lengths (serving prefill shape). Rows at positions past a
    # batch's valid length can have ZERO visible keys once the window
    # excludes the valid prefix — dense then emits uniform-softmax junk
    # while the kernel emits its guarded 0; serving reads neither, so
    # compare only the valid rows.
    lens = jnp.array([50, 192], dtype=jnp.int32)
    want_l = np.asarray(attention(
        q, k, v, causal=True, window=w, lengths=lens, kernel=False
    ))
    got_l = np.asarray(flash_attention(
        q, k, v, lens, causal=True, window=w, block_q=64, block_k=64,
        interpret=True,
    ))
    for bi, ln in enumerate([50, 192]):
        np.testing.assert_allclose(
            got_l[bi, :ln], want_l[bi, :ln], atol=2e-5, rtol=2e-5
        )


def test_windowed_flash_attention_grad(monkeypatch):
    """Windowed kernel forward + dense-recompute backward == dense grad
    (windowed-model training path)."""
    import importlib

    att = importlib.import_module("gofr_tpu.ops.attention")
    monkeypatch.setattr(att, "_FLASH_ENV", "1")
    b, s, n_heads, n_kv, hd, w = 1, 64, 4, 2, 32, 16
    key = jax.random.PRNGKey(33)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, n_heads, hd))
    k = jax.random.normal(kk, (b, s, n_kv, hd))
    v = jax.random.normal(kv_, (b, s, n_kv, hd))

    got = att.attention(q, k, v, causal=True, window=w)
    want = att.attention(q, k, v, causal=True, window=w, kernel=False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )

    def loss_kernel(q):
        return jnp.sum(att.attention(q, k, v, causal=True, window=w) ** 2)

    def loss_dense(q):
        return jnp.sum(
            att.attention(q, k, v, causal=True, window=w, kernel=False) ** 2
        )

    gk = jax.grad(loss_kernel)(q)
    gd = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(
        np.asarray(gk), np.asarray(gd), atol=1e-4, rtol=1e-4
    )
