"""CLI app tests (reference ``cmd_test.go`` patterns: route matching, flag
parsing, stdout/stderr split)."""

import io
from dataclasses import dataclass

from gofr_tpu.cli import CMDApp, CMDRequest
from gofr_tpu.config import MockConfig


def make_app() -> CMDApp:
    return CMDApp(config=MockConfig({}))


def run(app, argv):
    out, err = io.StringIO(), io.StringIO()
    code = app.run(argv, out=out, err=err)
    return code, out.getvalue(), err.getvalue()


def test_subcommand_dispatch():
    app = make_app()

    @app.sub_command("^hello")
    def hello(ctx):
        return "Hello World!"

    code, out, err = run(app, ["hello"])
    assert (code, out.strip(), err) == (0, "Hello World!", "")


def test_unknown_command():
    app = make_app()
    app.sub_command("^known", lambda ctx: "ok")
    code, out, err = run(app, ["unknown"])
    assert code == 1
    assert "No Command Found!" in err


def test_flags_become_params():
    app = make_app()

    @app.sub_command("^greet")
    def greet(ctx):
        return f"Hi {ctx.param('name')}, verbose={ctx.param('verbose')}"

    code, out, _ = run(app, ["greet", "-name=Ada", "--verbose"])
    assert "Hi Ada, verbose=true" in out


def test_bind_dataclass():
    @dataclass
    class Args:
        name: str = ""
        count: int = 0

    req = CMDRequest(["run", "-name=x", "-count=3"])
    args = req.bind(Args)
    assert args == Args(name="x", count=3)
    assert req.command == "run"


def test_handler_error_to_stderr():
    app = make_app()

    @app.sub_command("^fail")
    def fail(ctx):
        raise ValueError("boom")

    code, out, err = run(app, ["fail"])
    assert code == 1
    assert "boom" in err
    assert out == ""


def test_regex_first_match_wins():
    app = make_app()
    app.sub_command("^job run", lambda ctx: "specific")
    app.sub_command("^job", lambda ctx: "generic")
    _, out, _ = run(app, ["job", "run"])
    assert out.strip() == "specific"
