"""Runtime lock-discipline validator suite (gofr_tpu/analysis/lockcheck).

Interleavings are STATED, not raced: the order graph persists for the
registry's lifetime, so the two halves of an inversion are driven
sequentially — one thread runs A→B to completion, then another runs
B→A — and the detector must still catch the deadlock the collision
would have produced. No sleeps-as-synchronization anywhere.
"""

import threading

import pytest

from gofr_tpu.analysis import lockcheck
from gofr_tpu.analysis.lockcheck import InstrumentedLock, LockCheckError

_PLAIN_LOCK_TYPE = type(threading.Lock())


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    """Arm the validator with a FRESH registry per test (the module
    global would otherwise leak one test's order graph into the next)."""
    monkeypatch.setenv("TPU_LOCKCHECK", "1")
    monkeypatch.setattr(lockcheck, "_registry", None)
    yield
    monkeypatch.setattr(lockcheck, "_registry", None)


def _run(fn):
    """Run fn on its own thread to completion (distinct thread ident)."""
    exc = []

    def wrapped():
        try:
            fn()
        except BaseException as e:  # surfaced below
            exc.append(e)

    t = threading.Thread(target=wrapped)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    if exc:
        raise exc[0]


# ----------------------------------------------------------------------
# construction: the disabled path builds NOTHING
# ----------------------------------------------------------------------


def test_disabled_make_lock_returns_plain_lock(monkeypatch):
    # This is the whole overhead story for the BENCH_LOOP A/B: with
    # TPU_LOCKCHECK unset there is no wrapper to measure — make_lock
    # hands back the exact primitive the code used before.
    for off in ("0", "", "false", "no"):
        monkeypatch.setenv("TPU_LOCKCHECK", off)
        lock = lockcheck.make_lock("Engine._submit_lock")
        assert type(lock) is _PLAIN_LOCK_TYPE
    assert lockcheck._registry is None  # not even the registry exists
    lockcheck.note_device_sync("window_fetch")  # one is-None test, no-op
    assert lockcheck.violations() == []


def test_enabled_make_lock_returns_instrumented_wrapper():
    lock = lockcheck.make_lock("Pool._lock")
    assert isinstance(lock, InstrumentedLock)
    assert lock.name == "Pool._lock"
    with lock:
        assert lock.locked()
    assert not lock.locked()
    assert lockcheck.violations() == []


# ----------------------------------------------------------------------
# order inversion
# ----------------------------------------------------------------------


def test_inversion_detected_across_sequential_threads():
    a = lockcheck.make_lock("Engine._submit_lock")
    b = lockcheck.make_lock("Pool._lock")

    def forward():  # the submit path: engine -> pool
        with a:
            with b:
                pass

    def backward():  # the scaler path: pool -> engine
        with b:
            with a:
                pass

    _run(forward)
    assert lockcheck.violations() == []  # one order alone is fine
    _run(backward)
    (v,) = lockcheck.violations()
    assert v.kind == "order-inversion"
    assert "Engine._submit_lock" in v.message
    assert "Pool._lock" in v.message
    assert v.held == ("Pool._lock",)
    with pytest.raises(AssertionError, match="order-inversion"):
        lockcheck.assert_clean()


def test_transitive_inversion_through_a_middle_lock():
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    c = lockcheck.make_lock("C")

    def one():  # A -> B
        with a, b:
            pass

    def two():  # B -> C
        with b, c:
            pass

    def three():  # C -> A closes the 3-cycle
        with c, a:
            pass

    _run(one)
    _run(two)
    assert lockcheck.violations() == []
    _run(three)
    kinds = [v.kind for v in lockcheck.violations()]
    assert "order-inversion" in kinds


def test_consistent_global_order_stays_clean():
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    for _ in range(3):
        _run(lambda: a.acquire() and b.acquire())
        # release from the main thread (also exercises tolerance)
        b.release()
        a.release()
    lockcheck.assert_clean()


# ----------------------------------------------------------------------
# self-deadlock: raise, don't hang
# ----------------------------------------------------------------------


def test_blocking_self_reacquisition_raises_instead_of_hanging():
    lock = lockcheck.make_lock("Ledger._lock")
    with lock:
        with pytest.raises(LockCheckError, match="would deadlock"):
            lock.acquire()
    kinds = [v.kind for v in lockcheck.violations()]
    assert kinds == ["self-deadlock"]


def test_nonblocking_reacquisition_just_fails_like_a_lock():
    lock = lockcheck.make_lock("Ledger._lock")
    with lock:
        assert lock.acquire(blocking=False) is False
    # try-acquire losing is normal lock behavior, not a violation
    assert lockcheck.violations() == []


# ----------------------------------------------------------------------
# device sync under a held lock
# ----------------------------------------------------------------------


def test_device_sync_under_lock_is_recorded():
    lock = lockcheck.make_lock("SchedulerMixin._submit_lock")
    lockcheck.note_device_sync("decode_window_fetch")
    assert lockcheck.violations() == []  # nothing held: fine
    with lock:
        lockcheck.note_device_sync("decode_window_fetch")
    (v,) = lockcheck.violations()
    assert v.kind == "device-sync-under-lock"
    assert "decode_window_fetch" in v.message
    assert v.held == ("SchedulerMixin._submit_lock",)


# ----------------------------------------------------------------------
# cross-thread release (the profiler capture-slot idiom)
# ----------------------------------------------------------------------


def test_cross_thread_release_is_tolerated():
    busy = lockcheck.make_lock("ProfilerCapture._busy")
    other = lockcheck.make_lock("ProfilerCapture._state_lock")
    assert busy.acquire(blocking=False)  # scheduler thread takes the slot
    _run(busy.release)  # capture thread releases it
    # The slot is free again and the holder stack is clean: a later
    # acquisition under another lock must not see a stale entry.
    with other:
        with busy:
            pass
    lockcheck.assert_clean()


# ----------------------------------------------------------------------
# reset / assert_clean
# ----------------------------------------------------------------------


def test_reset_drops_violations_and_learned_order():
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    _run(lambda: (a.acquire(), b.acquire(), b.release(), a.release()))
    lockcheck.reset()
    # The old A->B edge must not indict the new order: one test's lock
    # order must not leak into another's.
    _run(lambda: (b.acquire(), a.acquire(), a.release(), b.release()))
    lockcheck.assert_clean()


def test_reset_keeps_preexisting_locks_connected():
    # InstrumentedLock captures its registry at construction; reset()
    # must clear that registry IN PLACE, not swap in a fresh one —
    # otherwise every lock minted before the reset (module-level locks,
    # engine fixtures from earlier tests) reports into a registry
    # nobody reads and its violations vanish.
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    lockcheck.reset()
    with a:
        lockcheck.note_device_sync("post_reset_sync")
    found = lockcheck.violations()
    assert [v.kind for v in found] == ["device-sync-under-lock"]
    lockcheck.reset()
    _run(lambda: (a.acquire(), b.acquire(), b.release(), a.release()))
    _run(lambda: (b.acquire(), a.acquire(), a.release(), b.release()))
    assert [v.kind for v in lockcheck.violations()] == ["order-inversion"]


def test_assert_clean_lists_every_violation():
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    _run(lambda: (a.acquire(), b.acquire(), b.release(), a.release()))
    _run(lambda: (b.acquire(), a.acquire(), a.release(), b.release()))
    with a:
        lockcheck.note_device_sync("window")
    with pytest.raises(AssertionError) as err:
        lockcheck.assert_clean()
    text = str(err.value)
    assert "order-inversion" in text and "device-sync-under-lock" in text
