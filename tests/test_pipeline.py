"""Pipeline (pp) and context (cp) parallel training on the 8-dev CPU mesh."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from gofr_tpu.models.registry import get_model
from gofr_tpu.parallel import make_mesh, make_train_step, pipeline_layer_fn


def _f32_tiny():
    return dataclasses.replace(get_model("llama-tiny").config, dtype=jnp.float32)


def test_pipeline_spmd_matches_sequential():
    """A pipelined stack of elementwise 'layers' equals the plain scan."""
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))  # 8 layers, D=16
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))  # b=4

    def layers_fn(act, lp_stack, extras):
        def body(h, w_l):
            return jnp.tanh(h * w_l[None, :]), None

        act, _ = lax.scan(body, act, lp_stack)
        return act

    want, _ = lax.scan(lambda h, wl: (jnp.tanh(h * wl[None, :]), None), x, w)
    run = pipeline_layer_fn(layers_fn, mesh, n_microbatches=2)
    got = run(x, w, ())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_pipeline_train_step_matches_unpipelined_loss():
    cfg = _f32_tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)

    mesh_ref = make_mesh({"dp": 1, "tp": 1}, devices=jax.devices()[:1])
    init_ref, step_ref, _ = make_train_step(cfg, mesh_ref, sp=False)
    p_ref, o_ref = init_ref(jax.random.PRNGKey(0))
    loss_ref, _, _ = step_ref(p_ref, o_ref, tokens)

    mesh_pp = make_mesh({"dp": 2, "pp": 2}, devices=jax.devices()[:4])
    init_pp, step_pp, _ = make_train_step(cfg, mesh_pp, sp=False, n_microbatches=2)
    p_pp, o_pp = init_pp(jax.random.PRNGKey(0))
    assert p_pp["layers"]["wq"].sharding.spec[0] == "pp"
    loss_pp, p_pp, o_pp = step_pp(p_pp, o_pp, tokens)
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-4)
    # And training actually progresses.
    loss2, _, _ = step_pp(p_pp, o_pp, tokens)
    assert float(loss2) < float(loss_pp)


@pytest.mark.parametrize("cp_impl", ["ring", "ulysses"])
def test_cp_train_step_matches_uncp_loss(cp_impl):
    cfg = _f32_tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)

    mesh_ref = make_mesh({"dp": 1, "tp": 1}, devices=jax.devices()[:1])
    init_ref, step_ref, _ = make_train_step(cfg, mesh_ref, sp=False)
    p_ref, o_ref = init_ref(jax.random.PRNGKey(0))
    loss_ref, _, _ = step_ref(p_ref, o_ref, tokens)

    mesh_cp = make_mesh({"dp": 2, "cp": 4})
    init_cp, step_cp, _ = make_train_step(
        cfg, mesh_cp, sp=False, cp_impl=cp_impl
    )
    p_cp, o_cp = init_cp(jax.random.PRNGKey(0))
    loss_cp, _, _ = step_cp(p_cp, o_cp, tokens)
    np.testing.assert_allclose(float(loss_cp), float(loss_ref), rtol=1e-4)


def test_pp_plus_cp_train_step_matches_reference_loss():
    """pp × cp in ONE mesh: cp rides GSPMD (dense sharded-softmax
    attention) inside the pipeline's partial-manual shard_map — the ring
    implementations can't nest there, the auto-axis formulation can."""
    cfg = _f32_tiny()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)

    mesh_ref = make_mesh({"dp": 1, "tp": 1}, devices=jax.devices()[:1])
    init_ref, step_ref, _ = make_train_step(cfg, mesh_ref, sp=False)
    p_ref, o_ref = init_ref(jax.random.PRNGKey(0))
    loss_ref, _, _ = step_ref(p_ref, o_ref, tokens)

    mesh = make_mesh({"dp": 2, "pp": 2, "cp": 2})
    init_state, train_step, _ = make_train_step(
        cfg, mesh, sp=False, n_microbatches=2
    )
    params, opt_state = init_state(jax.random.PRNGKey(0))
    loss, params, opt_state = train_step(params, opt_state, tokens)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-4)


def test_cp_with_tp_train_step():
    """cp composes with tp (and sp constraints) in one mesh."""
    cfg = _f32_tiny()
    mesh = make_mesh({"dp": 2, "cp": 2, "tp": 2})
    init_state, train_step, _ = make_train_step(cfg, mesh, sp=True)
    params, opt_state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    loss, params, opt_state = train_step(params, opt_state, tokens)
    assert np.isfinite(float(loss))
