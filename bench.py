"""Headline benchmark — flagship LLM serving throughput on TPU.

Boots the serving engine (continuous batching, fused decode+sample, donated
KV cache) with the largest Llama-family config that fits the available chip,
runs concurrent generation, and prints ONE JSON line:

    {"metric": "decode_tokens_per_sec_per_chip", "value": N,
     "unit": "tok/s/chip", "vs_baseline": N/1000}

``vs_baseline``: the reference (GoFr) publishes no perf numbers
(BASELINE.md), so the denominator is a fixed 1000 tok/s/chip nominal
target for a ~1B bf16 model on one v5e — chosen once so the ratio is
comparable across rounds. Details (TTFT p50/p99, per-request rates) go to
stderr.

Env knobs: BENCH_MODEL (default llama-1b on TPU, llama-tiny on CPU),
BENCH_REQUESTS (default 64), BENCH_NEW_TOKENS (default 128),
BENCH_SLOTS (default 32), BENCH_MAX_LEN (default 1024),
BENCH_WINDOW (default 8), BENCH_DEPTH (default 2), BENCH_MEGA
(mega-window dispatch amortization; default 8 on TPU, 0 = streaming
pipelined mode elsewhere), BENCH_PREFILL_DEPTH (multi-chunk prefill),
BENCH_QUANT (default int8 on TPU — weight-only int8, the production
serving configuration; set BENCH_QUANT=none for bf16 weights),
BENCH_LORA / BENCH_LORA_RANK (N random adapters, requests round-robin
over base + adapters — the multi-LoRA overhead A/B),
BENCH_PREFIX_WORKLOAD=1 (repeated-prefix burst: one shared
BENCH_PREFIX_TOKENS=512 preamble + distinct suffixes on a paged engine;
reports prefix hit-token ratio and warm-vs-cold TTFT;
BENCH_AUTO_PREFIX=0 runs the same workload with the radix cache off —
the prefix-caching A/B),
BENCH_TP_WORKLOAD=1 (GSPMD-sharded serving A/B: the SAME burst on a
tp=1 then a tp=2 engine — token-identity enforced, the tp-invariance
contract — emitting tp1_tps/tp2_tps/tp_speedup in one JSON line; on the
CPU backend 8 virtual devices are forced and the row is degraded/NOT
comparable, it exists so the perf trajectory captures sharded-engine
step time until a real TPU window lands),
BENCH_TENANT_WORKLOAD=1 (mixed-tenant burst: one hog tenant floods the
queue while BENCH_TENANTS=3 well-behaved tenants submit small requests;
the same burst runs with fairness shedding off then on
(BENCH_TENANT_FAIR_SHARE=0.3) and the JSON line carries tenant_count,
per-tenant tok/s spread, the well-behaved tenants' TTFT under both
policies, hog fair-share shed counts, and the TTFT SLO's 5m burn rate),
BENCH_OVERLOAD_WORKLOAD=1 (overload-storm A/B: batch-class flood +
interactive arrivals under an always-breaching TTFT SLO, run with the
brownout ladder off then on — the JSON line carries
interactive_goodput_{off,on}, ttft_p99_{off,on}_ms,
shed_{batch,interactive}_total, and max_brownout_level),
BENCH_TIER_WORKLOAD=1 (disaggregated-tier transfer-leg A/B: the same
prefill-heavy burst through a prefill+decode pool with the transfer leg
pinned to host-bounce then to the device leg — the JSON line carries
transfer_ms_{host,device} p50/p95, per-leg decode-tier cold TTFT, and
tier_transfers_total{leg,result}; acceptance = device p50 strictly
below host),
BENCH_SPEC_WORKLOAD=1 (n-gram speculation A/B: a repeated-text burst
on spec=0 vs spec=BENCH_SPEC_G=2 engines, emitting plain/spec tok/s,
the measured app_tpu_spec_tokens_per_step acceptance, and the
per-request greedy-identity verdict — the default-on decision data),
BENCH_CONTROL_WORKLOAD=1 (control-plane A/B: a diurnal hog-tenant ramp
over a small queue with BENCH_TENANTS=3 well-behaved tenants, run with
the control plane off then on — the JSON line carries per-tenant
goodput min/max under both policies, the hog's highest per-tenant
ladder level, the predictive loop's scale lead time, and the plane's
degraded-signal / eval-error counts),
BENCH_ASYNC_WORKLOAD=1 (durable async-serving idle-soak A/B: the same
interactive trickle with the async plane off then on against a
request-topic backlog — with poison messages riding along so the
redelivery/dead-letter path is priced too — emitting async_tps,
interactive_ttft_p95_{off,on}_ms, redelivered and dead_lettered; the
claim priced is that async soaks idle capacity WITHOUT moving
interactive TTFT).
Workload: BENCH_ARRIVAL_MS / BENCH_TOKEN_SPREAD (TPU default 25 / 0.5 —
steady-state; the reported value is then the mid-window sustained rate,
with the end-to-end rate in e2e_tps; set both to 0 for the synchronized
burst pre-r4 campaign rows used).
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pct(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 on empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _latency_fields(results: list) -> dict:
    """Per-request TTFT and inter-token-latency percentiles (ms) for
    the JSON result line, so BENCH_* trajectories capture tail latency
    alongside throughput. ITL per request = (duration - ttft) over the
    gaps between its generated tokens; requests with <2 tokens have no
    gap and are skipped."""
    ttfts = sorted(r.ttft_s * 1e3 for r in results)
    itls = sorted(
        (r.duration_s - r.ttft_s) / (len(r.token_ids) - 1) * 1e3
        for r in results if len(r.token_ids) >= 2
    )
    return {
        "ttft_p50": round(_pct(ttfts, 0.50), 2),
        "ttft_p95": round(_pct(ttfts, 0.95), 2),
        "ttft_p99": round(_pct(ttfts, 0.99), 2),
        "itl_p50": round(_pct(itls, 0.50), 3),
        "itl_p95": round(_pct(itls, 0.95), 3),
        "itl_p99": round(_pct(itls, 0.99), 3),
    }


def _device_resource_fields(engine) -> dict:
    """Device-resource fields for the JSON result line (ISSUE 11):
    total XLA compiles, compiles that fired AFTER the warm-up fence
    (always a fixed-shape bug — see ``_recompile_guard``), and peak
    per-device HBM (the runtime's own peak when the platform reports
    one, else the ledger's per-device accounting)."""
    stats = engine.compile_stats()
    ledger = engine.hbm_ledger()
    # The ledger snapshot already carries the platform cross-check
    # (mesh-aware device pick); reuse it rather than re-probing.
    mem = ledger.get("device") or {}
    peak = int(
        mem.get("peak_bytes_in_use") or mem.get("bytes_in_use") or 0
    )
    return {
        "compiles_total": int(stats["total"]),
        "steady_state_recompiles": int(stats["steady_state_recompiles"]),
        "hbm_peak_bytes": max(peak, int(ledger.get("per_device_bytes", 0))),
    }


def _loop_fields(engine) -> dict:
    """Scheduler-loop profiler fields for the JSON result line
    (ISSUE 15): the loop's busy fraction, the host-bookkeeping share
    of busy time (THE "is host bookkeeping starving the TPU" number a
    real-TPU row must carry next to tok/s), stall count, and per-phase
    rolling p50s. Empty marker when the layer is off — the
    TPU_LOOP_PROFILE=0 overhead A/B."""
    prof = getattr(engine, "_loop_prof", None)
    if prof is None:
        return {"loop_profile": False}
    return {
        "loop_util": round(prof.utilization(), 4),
        "host_overhead_ratio": round(prof.host_overhead_ratio(), 4),
        "loop_stalls": int(prof.stalls),
        "loop_phase_p50_ms": prof.phase_p50_ms(),
    }


def _recompile_guard(engine) -> None:
    """The fixed-shape contract as a bench guard (the compile-tracker
    twin of BENCH_TP_WORKLOAD's token-identity exit): any XLA compile
    after ``mark_steady_state`` means the measured run was serialized
    behind a trace+compile — the number would be garbage AND the
    serving config has a shape-discipline bug. Exit 6, no JSON."""
    stats = engine.compile_stats()
    if stats["steady_state_recompiles"]:
        log(f"bench: {stats['steady_state_recompiles']} STEADY-STATE "
            f"RECOMPILE(S) after the warm-up fence "
            f"({ {k: v['compiles'] for k, v in stats['programs'].items() if v['compiles']} }) "
            f"— fixed-shape contract broken; refusing to report a "
            f"compile-serialized number")
        os._exit(6)


def _extract_json_line(out: str) -> str | None:
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
                return line
            except json.JSONDecodeError:
                continue
    return None


def _previous_bench_row(metric: str) -> "tuple[str | None, dict | None]":
    """Latest committed ``BENCH_*.json`` row for ``metric``. Each
    committed artifact wraps one run ({n, cmd, rc, tail, parsed}); the
    row is the wrapper's ``parsed`` object when the harvester filled
    it, else the last JSON line fished out of ``tail``. Runs that
    never emitted a row (wedged init, watchdog exits) simply don't
    match — the trajectory is computed against the newest run that
    actually reported."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(
        glob.glob(os.path.join(here, "BENCH_*.json")), reverse=True
    ):
        try:
            with open(path, encoding="utf-8") as fh:
                wrapper = json.load(fh)
        except (OSError, ValueError):
            continue
        row = wrapper.get("parsed") if isinstance(wrapper, dict) else None
        if not isinstance(row, dict):
            line = _extract_json_line(str(
                (wrapper or {}).get("tail", "")
                if isinstance(wrapper, dict) else ""
            ))
            row = json.loads(line) if line else None
        if isinstance(row, dict) and row.get("metric") == metric:
            return os.path.basename(path), row
    return None, None


def _trajectory_fields(current: dict) -> dict:
    """Run-over-run trajectory (ISSUE 19 satellite): compare this run's
    resource fields against the newest committed ``BENCH_*.json`` row
    of the same metric — peak-HBM delta and per-phase scheduler-loop
    p50 deltas — so a regression shows up IN the row that introduced
    it, not three PRs later when someone diffs artifacts by hand.
    ``trajectory: null`` when no prior run of this metric ever
    reported."""
    prev_name, prev = _previous_bench_row(str(current.get("metric", "")))
    if prev is None:
        return {"trajectory": None}
    traj: dict = {"prev_run": prev_name}
    if "hbm_peak_bytes" in current and "hbm_peak_bytes" in prev:
        traj["hbm_peak_delta_bytes"] = (
            int(current["hbm_peak_bytes"]) - int(prev["hbm_peak_bytes"])
        )
    cur_p = current.get("loop_phase_p50_ms")
    prev_p = prev.get("loop_phase_p50_ms")
    if isinstance(cur_p, dict) and isinstance(prev_p, dict):
        traj["loop_phase_p50_delta_ms"] = {
            k: round(float(cur_p[k]) - float(prev_p[k]), 3)
            for k in cur_p if k in prev_p
        }
    if "value" in prev:
        traj["prev_value"] = prev["value"]
    return {"trajectory": traj}


def run_with_retry() -> int:
    """Round-2 lesson (VERDICT weak #1): a wedged axon relay made the child
    hang ~26 minutes in engine-init remote compiles — PAST the old
    init-only watchdog — while 5 × 2400s of attempt budget overran the
    driver's whole window. Round-3 contract:

    * the TPU attempts share ONE wall-clock budget (``BENCH_TOTAL_BUDGET``,
      default 1500s ≈ 25 min); each attempt gets ``min(BENCH_TIMEOUT,
      remaining)`` with a parent-side kill AND a child-side whole-run
      watchdog (``BENCH_CHILD_WALL`` env → ``os._exit(3)`` with the stage
      named, so a timeout tail says WHERE it hung);
    * when the budget is spent (or attempts exhausted), the degraded CPU
      fallback ALWAYS fires with its own 900s window;
    * the emitted JSON carries ``platform`` + ``degraded`` fields so a
      fallback number can never impersonate a TPU number.
    """
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "3"))
    per_attempt = float(os.environ.get("BENCH_TIMEOUT", "600"))
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "1500"))
    me = os.path.abspath(__file__)
    start = time.time()
    for i in range(attempts):
        remaining = total_budget - (time.time() - start)
        if remaining < 120:
            log(f"bench: TPU attempt budget spent "
                f"({total_budget:.0f}s) — going degraded")
            break
        this_timeout = min(per_attempt, remaining)
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        env["BENCH_CHILD_WALL"] = str(this_timeout - 15.0)
        log(f"bench attempt {i + 1}/{attempts}: timeout {this_timeout:.0f}s "
            f"({remaining:.0f}s budget left)")
        try:
            proc = subprocess.run(
                [sys.executable, me], env=env, stdout=subprocess.PIPE,
                timeout=this_timeout,
            )
        except subprocess.TimeoutExpired:
            log(f"bench attempt {i + 1}/{attempts}: parent-side kill after "
                f"{this_timeout:.0f}s — child watchdog failed to fire")
            continue
        out = proc.stdout.decode("utf-8", "replace")
        if proc.returncode == 0:
            line = _extract_json_line(out)
            if line is not None:
                print(line, flush=True)
                return 0
            log(f"bench attempt {i + 1}/{attempts}: rc=0 but no JSON line")
        else:
            log(f"bench attempt {i + 1}/{attempts}: rc={proc.returncode}")
        if i < attempts - 1:
            delay = 15.0
            log(f"retrying in {delay:.0f}s (transient TPU relay flakes "
                f"recover on re-init)")
            time.sleep(delay)
    # Degraded fallback: CPU + tiny model. The JSON line carries
    # platform="cpu", degraded=true — it exists so the round artifact
    # parses instead of being rc!=0, and is NOT comparable to a TPU run.
    log("DEGRADED: falling back to CPU llama-tiny — value NOT comparable "
        "to TPU; emitted JSON is marked platform=cpu degraded=true")
    env = dict(os.environ)
    env.update(BENCH_CHILD="1", JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    # Scrub every TPU-sized knob: a driver-exported 64×256-token config
    # would blow the fallback's wall clock on CPU and lose the artifact.
    for knob in ("BENCH_MODEL", "BENCH_NEW_TOKENS", "BENCH_SLOTS",
                 "BENCH_MAX_LEN", "BENCH_QUANT", "BENCH_SPEC",
                 "BENCH_KV_BLOCK", "BENCH_KV_QUANT", "GOFR_TPU_FLASH_DECODE",
                 "BENCH_ARRIVAL_MS", "BENCH_TOKEN_SPREAD", "BENCH_MEGA",
                 "BENCH_LORA", "BENCH_LORA_RANK"):
        env.pop(knob, None)
    env["BENCH_REQUESTS"] = "8"
    # The production dispatch-amortizer is part of the engine now; the
    # fallback row reports the engine as configured, labeled degraded.
    env["BENCH_MEGA"] = "8"
    env["BENCH_CHILD_WALL"] = "870"
    try:
        proc = subprocess.run(
            [sys.executable, me], env=env, stdout=subprocess.PIPE, timeout=900,
        )
        line = _extract_json_line(proc.stdout.decode("utf-8", "replace"))
        if proc.returncode == 0 and line is not None:
            print(line, flush=True)
            return 0
    except subprocess.TimeoutExpired:
        pass
    log("bench: even the CPU fallback failed")
    return 1


def _decode_attn_ab(engine, n_slots: int, kv_quant: str) -> None:
    """In-graph decode-attention A/B (kernel grid vs fused dense).

    The r4 probe timed 30 sequential un-donated dispatches, so per-call
    dispatch overhead (~relay RTT) swamped device time: it printed
    per-layer numbers whose sum exceeded the measured full step by 40×
    and inverted the kernel/dense ordering (VERDICT r4 weak #4). This
    probe chains the op M times inside ONE jitted program — the output
    feeds the next iteration's query, so XLA can't elide or reorder
    iterations — and differences two trip counts: constant per-dispatch
    overhead cancels exactly, leaving pure per-layer device time that
    sums consistently with the measured step.
    """
    import jax
    import jax.numpy as jnp

    from gofr_tpu.ops.attention import decode_attention
    from gofr_tpu.ops.kv_cache import quantize_kv

    cfg = engine.cfg
    S, T = n_slots, engine.max_len
    key = jax.random.PRNGKey(0)
    qa = jax.random.normal(key, (S, cfg.n_heads, cfg.head_dim), jnp.bfloat16)
    kc = jax.random.normal(key, (S, cfg.n_kv_heads, T, cfg.head_dim), jnp.bfloat16)
    vc = kc + 1
    ks = vs = None
    if kv_quant:  # mirror the served cache dtype (int8 + scale planes)
        kc, ksc = quantize_kv(kc)
        vc, vsc = quantize_kv(vc)
        rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
            s[:, :, None, :], (S, cfg.n_kv_heads, 8, T)
        ).astype(jnp.float32)
        ks, vs = rep8(ksc), rep8(vsc)
    lens = jnp.full((S,), T // 2, jnp.int32)  # typical half-full slots
    # Windowed models (mistral): measure the attention the engine
    # actually serves — the kernel skips out-of-window blocks, the dense
    # path can't, so the A/B verdict differs from the unwindowed one.
    window = getattr(cfg, "sliding_window", 0) or 0
    L = cfg.n_layers
    m1, m2 = L, 9 * L  # differenced trip counts (both amortize dispatch)
    for name, kern in (("kernel", True), ("dense", False)):
        try:

            def chained(q, k, v, le, sk, sv, m, kn=kern):
                def body(_, qc):
                    return decode_attention(
                        qc, k, v, le, k_scale=sk, v_scale=sv, kernel=kn,
                        window=window,
                    )

                return jax.lax.fori_loop(0, m, body, q)

            fn = jax.jit(chained, donate_argnums=(0,))
            times = {}
            for m in (m1, m2):
                md = jnp.int32(m)
                jax.block_until_ready(
                    fn(jnp.array(qa), kc, vc, lens, ks, vs, md)
                )  # compile (shared across m: trip count is traced)
                reps, out = 3, None
                t_ab = time.perf_counter()
                for _ in range(reps):
                    # Fresh query copy per call (the carry is donated);
                    # the D2D copy is per-call-constant → cancels in the
                    # difference below.
                    out = fn(jnp.array(qa), kc, vc, lens, ks, vs, md)
                jax.block_until_ready(out)
                times[m] = (time.perf_counter() - t_ab) / reps
            per = (times[m2] - times[m1]) / (m2 - m1) * 1e3
            const = times[m1] * 1e3 - per * m1
            wtag = f" window={window}" if window else ""
            log(f"profile: decode-attn[{name}] ({kv_quant or 'bf16'} kv"
                f"{wtag}) {per:.4f} ms/layer in-graph → ~{per * L:.2f} "
                f"ms/step attn total (per-dispatch const ≈{const:.1f} ms, "
                f"cancelled)")
        except Exception as exc:  # noqa: BLE001 — A/B is advisory
            log(f"profile: decode-attn[{name}] probe failed: {exc}")


def _prefill_attn_ab(engine, n_slots: int, kv_quant: str) -> None:
    """In-graph chunked-prefill attention A/B (kernel vs dense), same
    dispatch-cancelling differencing as ``_decode_attn_ab``. Answers
    whether the chunk kernel's length-skipping beats one fused dense op
    at the serving chunk shape (TTFT attribution)."""
    import jax
    import jax.numpy as jnp

    from gofr_tpu.ops.attention import cache_chunk_attention
    from gofr_tpu.ops.kv_cache import quantize_kv

    cfg = engine.cfg
    S, T, c = n_slots, engine.max_len, engine.prefill_chunk
    P = engine.prefill_batch
    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (P, c, cfg.n_heads, cfg.head_dim), jnp.bfloat16)
    kc = jax.random.normal(
        key, (S, cfg.n_kv_heads, T, cfg.head_dim), jnp.bfloat16
    )
    vc = kc + 1
    ks = vs = None
    if kv_quant:
        kc, ksc = quantize_kv(kc)
        vc, vsc = quantize_kv(vc)
        rep8 = lambda s: jnp.broadcast_to(  # noqa: E731
            s[:, :, None, :], (S, cfg.n_kv_heads, 8, T)
        ).astype(jnp.float32)
        ks, vs = rep8(ksc), rep8(vsc)
    slots = jnp.arange(P, dtype=jnp.int32) % S
    starts = jnp.full((P,), T // 2, jnp.int32)  # mid-prompt chunk
    lens = jnp.full((P,), c, jnp.int32)
    window = getattr(cfg, "sliding_window", 0) or 0
    L = cfg.n_layers
    m1, m2 = L, 9 * L
    for name, kern in (("kernel", True), ("dense", False)):
        try:

            def chained(q, k, v, sl, st, ln, sk, sv, m, kn=kern):
                def body(_, qc):
                    return cache_chunk_attention(
                        qc, k, v, sl, st, ln, k_scale=sk, v_scale=sv,
                        kernel=kn, window=window,
                    )

                return jax.lax.fori_loop(0, m, body, q)

            fn = jax.jit(chained, donate_argnums=(0,))
            times = {}
            for m in (m1, m2):
                md = jnp.int32(m)
                jax.block_until_ready(
                    fn(jnp.array(q), kc, vc, slots, starts, lens, ks, vs, md)
                )
                reps, out = 3, None
                t_ab = time.perf_counter()
                for _ in range(reps):
                    out = fn(
                        jnp.array(q), kc, vc, slots, starts, lens, ks, vs,
                        md,
                    )
                jax.block_until_ready(out)
                times[m] = (time.perf_counter() - t_ab) / reps
            per = (times[m2] - times[m1]) / (m2 - m1) * 1e3
            wtag = f" window={window}" if window else ""
            log(f"profile: prefill-attn[{name}] ({P}x{c} chunk, "
                f"{kv_quant or 'bf16'} kv{wtag}) {per:.4f} ms/layer "
                f"in-graph → ~{per * L:.2f} ms/chunk attn total")
        except Exception as exc:  # noqa: BLE001 — A/B is advisory
            log(f"profile: prefill-attn[{name}] probe failed: {exc}")


_STAGE = ["start", time.time()]


def _set_stage(name: str) -> None:
    _STAGE[0] = name
    _STAGE[1] = time.time()


def _prefix_workload(on_tpu: bool) -> None:
    """BENCH_PREFIX_WORKLOAD=1: repeated-prefix burst — every request
    shares one 512-token preamble and carries a distinct suffix, the
    shape real traffic (system prompts, few-shot preambles, multi-turn
    history) re-prefills today. Reports the prefix hit-token ratio and
    warm-vs-cold TTFT alongside the usual JSON line fields;
    BENCH_AUTO_PREFIX=0 runs the identical workload cold (the A/B).
    Self-contained: paged engine, no profile phase, CPU-safe."""
    import statistics

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    auto = os.environ.get("BENCH_AUTO_PREFIX", "1").lower() not in (
        "0", "false", "no",
    )
    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_requests = int(os.environ.get("BENCH_REQUESTS", "16" if on_tpu else "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "32" if on_tpu else "8"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "8"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "1024"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "128" if on_tpu else "64"))
    preamble_tokens = int(os.environ.get("BENCH_PREFIX_TOKENS", "512"))
    # Proactive eviction watermark A/B (BENCH_PREFIX_EVICT_WM, blocks;
    # 0 = shortfall-only eviction, the pre-watermark behavior).
    evict_wm = int(os.environ.get("BENCH_PREFIX_EVICT_WM", "0"))
    quant = os.environ.get("BENCH_QUANT", "int8" if on_tpu else "")
    if quant.lower() in ("none", "0"):
        quant = ""

    log(f"bench[prefix]: model={model} requests={n_requests} "
        f"preamble={preamble_tokens}tok kv_block={kv_block} "
        f"auto_prefix={auto} evict_wm={evict_wm}")
    _set_stage("engine-init")
    engine = InferenceEngine(
        model, n_slots=n_slots, max_len=max_len, tokenizer=ByteTokenizer(),
        window_k=int(os.environ.get("BENCH_WINDOW", "8")),
        pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
        quant=quant,
        kv_block=kv_block,
        auto_prefix=auto,
        prefix_evict_watermark=evict_wm,
        prefill_chunk=int(os.environ.get("BENCH_PREFILL_CHUNK", "256")),
    )
    engine.start_sync()

    # ByteTokenizer: 1 char = 1 token, so the shared preamble is exactly
    # preamble_tokens long and a multiple of nothing in particular —
    # the boundary block exercises the partial-block path. Clamp to what
    # the engine can actually admit (llama-tiny's config caps max_len at
    # 256 on the CPU fallback) while keeping ≥ 2 full KV blocks shared.
    cap = engine.max_prompt_tokens - new_tokens - 32
    if preamble_tokens > cap:
        # Never exceed the admissible prompt length — with a large
        # BENCH_KV_BLOCK on the CPU fallback, 2 full blocks may simply
        # not fit; warn rather than crash the first cold generate.
        preamble_tokens = max(cap, 1)
        log(f"bench[prefix]: preamble clamped to {preamble_tokens} tokens "
            f"(engine max prompt {engine.max_prompt_tokens})")
        if preamble_tokens < 2 * kv_block:
            log(f"bench[prefix]: WARNING preamble < 2 KV blocks "
                f"({kv_block} tok each) — little or nothing to share; "
                f"lower BENCH_KV_BLOCK or raise BENCH_MAX_LEN")
    preamble = "S" * preamble_tokens
    _set_stage("warmup")
    engine.generate_sync(
        "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
    )
    # Warm-up fence: the chunked-prefill and decode programs are
    # compiled; anything that compiles during the measured phase is a
    # fixed-shape bug (exit 6 below) and would serialize the burst.
    engine.mark_steady_state()

    _set_stage("measure")
    # COLD: the first preamble-carrying request prefills everything
    # (and, with auto_prefix, seeds the radix index as it retires).
    t0 = time.time()
    cold = engine.generate_sync(
        preamble + " request cold", max_new_tokens=new_tokens,
        temperature=0.0, stop_on_eos=False,
    )
    cold_ttft_ms = cold.ttft_s * 1e3
    # WARM burst: distinct suffixes behind the shared preamble.
    reqs = [
        engine.submit_generate(
            f"{preamble} request {i:04d}", max_new_tokens=new_tokens,
            temperature=0.0, stop_on_eos=False,
        )
        for i in range(n_requests)
    ]
    results = [r.future.result(timeout=1800) for r in reqs]
    wall = time.time() - t0
    warm_ttfts = sorted(r.ttft_s * 1e3 for r in results)
    warm_p50 = statistics.median(warm_ttfts)
    total_prompt = sum(
        len(f"{preamble} request {i:04d}") for i in range(n_requests)
    ) + len(preamble + " request cold")
    hit_tokens = engine._prefix_hit_tokens
    hit_ratio = hit_tokens / total_prompt if total_prompt else 0.0
    total_tokens = sum(len(r.token_ids) for r in results) + len(cold.token_ids)
    latency = _latency_fields([cold, *results])
    log(f"bench[prefix]: {total_tokens} tokens in {wall:.2f}s; "
        f"hit_tokens={hit_tokens}/{total_prompt} ({100 * hit_ratio:.1f}%); "
        f"TTFT cold={cold_ttft_ms:.1f}ms warm_p50={warm_p50:.1f}ms; "
        f"ttft p50/p95/p99={latency['ttft_p50']}/{latency['ttft_p95']}/"
        f"{latency['ttft_p99']}ms itl p50/p95/p99={latency['itl_p50']}/"
        f"{latency['itl_p95']}/{latency['itl_p99']}ms")
    device_fields = _device_resource_fields(engine)
    loop_fields = _loop_fields(engine)
    _recompile_guard(engine)
    engine.stop_sync()
    _set_stage("done")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(total_tokens / wall, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(total_tokens / wall / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "prefix",
        "auto_prefix": auto,
        "prefix_evict_wm": evict_wm,
        "prefix_hit_token_ratio": round(hit_ratio, 4),
        "prefix_hit_tokens": int(hit_tokens),
        "cold_ttft_ms": round(cold_ttft_ms, 2),
        "warm_ttft_p50_ms": round(warm_p50, 2),
        **latency,
        **device_fields,
        **loop_fields,
    }), flush=True)
    os._exit(0)


def _loop_workload(on_tpu: bool) -> None:
    """BENCH_LOOP_WORKLOAD=1: the scheduler-loop profiler overhead A/B
    (ISSUE 15) — the identical steady burst with TPU_LOOP_PROFILE off
    then on, pinning the layer's cost next to the signals it buys
    (loop utilization, host-overhead ratio, per-phase p50s). The
    profiler's own measured summarization cost rides the line too.
    Self-contained: paged engine, no profile phase, CPU-safe."""
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_requests = int(os.environ.get("BENCH_REQUESTS", "16" if on_tpu else "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "32" if on_tpu else "16"))
    eng_kw = dict(
        n_slots=int(os.environ.get("BENCH_SLOTS", "8")),
        max_len=int(os.environ.get("BENCH_MAX_LEN", "1024")),
        window_k=int(os.environ.get("BENCH_WINDOW", "8")),
        pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
        kv_block=int(os.environ.get("BENCH_KV_BLOCK", "128" if on_tpu else "64")),
        auto_prefix=True,
        prefill_chunk=int(os.environ.get("BENCH_PREFILL_CHUNK", "256")),
        tokenizer=ByteTokenizer(),
    )
    quant = os.environ.get("BENCH_QUANT", "int8" if on_tpu else "")
    if quant.lower() not in ("none", "0", ""):
        eng_kw["quant"] = quant
    log(f"bench[loop]: model={model} requests={n_requests} "
        f"new_tokens={new_tokens} — TPU_LOOP_PROFILE off/on A/B")

    def run(profile: bool) -> tuple[float, object]:
        _set_stage("engine-init")
        engine = InferenceEngine(model, loop_profile=profile, **eng_kw)
        engine.start_sync()
        _set_stage("warmup")
        engine.generate_sync(
            "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        engine.mark_steady_state()
        _set_stage("measure")
        t0 = time.time()
        reqs = [
            engine.submit_generate(
                f"loop burst request {i:04d}", max_new_tokens=new_tokens,
                temperature=0.0, stop_on_eos=False,
            )
            for i in range(n_requests)
        ]
        results = [r.future.result(timeout=1800) for r in reqs]
        wall = time.time() - t0
        tokens = sum(len(r.token_ids) for r in results)
        _recompile_guard(engine)
        return tokens / wall, engine

    tps_off, eng_off = run(False)
    eng_off.stop_sync()
    tps_on, eng_on = run(True)
    loop_fields = _loop_fields(eng_on)
    prof = eng_on._loop_prof
    self_overhead_s = float(prof.self_overhead_s) if prof is not None else 0.0
    passes = int(prof.passes) if prof is not None else 0
    eng_on.stop_sync()
    _set_stage("done")
    overhead_pct = (
        (tps_off - tps_on) / tps_off * 100.0 if tps_off > 0 else 0.0
    )
    log(f"bench[loop]: off={tps_off:.1f} on={tps_on:.1f} tok/s "
        f"({overhead_pct:+.2f}% overhead); loop_util="
        f"{loop_fields.get('loop_util')} host_overhead_ratio="
        f"{loop_fields.get('host_overhead_ratio')}; profiler self-cost "
        f"{self_overhead_s * 1e3:.2f}ms over {passes} passes")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tps_on, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tps_on / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "loop-profile",
        "tps_profile_off": round(tps_off, 2),
        "tps_profile_on": round(tps_on, 2),
        "loop_profile_overhead_pct": round(overhead_pct, 2),
        "loop_self_overhead_ms": round(self_overhead_s * 1e3, 3),
        "loop_passes": passes,
        **loop_fields,
    }), flush=True)
    os._exit(0)


def _tenant_workload(on_tpu: bool) -> None:
    """BENCH_TENANT_WORKLOAD=1: mixed-tenant burst — one hog tenant
    floods the queue with long-prompt requests while N well-behaved
    tenants submit small interactive ones, the shape a multi-tenant pod
    degrades under today. Runs the SAME burst twice: fairness shedding
    off, then on (``TPU_TENANT_FAIR_SHARE``, default
    BENCH_TENANT_FAIR_SHARE=0.3) — the A/B that decides whether the
    hog's burst degrades the hog or the fleet. Reports per-tenant tok/s
    spread, the well-behaved tenants' TTFT under both policies, the
    hog's fair-share shed count, and the TTFT SLO's 5m burn rate.
    Self-contained: paged engine, no profile phase, CPU-safe."""
    from gofr_tpu.errors import ErrorTooManyRequests
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_tenants = int(os.environ.get("BENCH_TENANTS", "3"))
    wb_requests = int(os.environ.get("BENCH_REQUESTS", "4"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "16" if on_tpu else "8"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "2"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "256"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "32"))
    hog_requests = int(os.environ.get("BENCH_HOG_REQUESTS", "16"))
    fair_share = float(os.environ.get("BENCH_TENANT_FAIR_SHARE", "0.3"))
    slo_ttft_ms = float(os.environ.get("BENCH_SLO_TTFT_MS", "1000"))

    log(f"bench[tenant]: model={model} tenants={n_tenants} "
        f"wb_requests={wb_requests} hog_requests={hog_requests} "
        f"fair_share={fair_share} slots={n_slots}")

    def run(share: float) -> dict:
        _set_stage(f"engine-init-fair{share}")
        engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len,
            tokenizer=ByteTokenizer(),
            window_k=int(os.environ.get("BENCH_WINDOW", "8")),
            pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
            kv_block=kv_block,
            # The queue-token budget the fair share divides: small
            # enough that the hog's flood saturates it.
            queue_max_tokens=int(os.environ.get(
                "BENCH_QUEUE_TOKENS", "512"
            )),
            tenant_ledger=True,
            tenant_fair_share=share,
            slo_ttft_ms=slo_ttft_ms,
            seed=0,
        )
        engine.start_sync()
        _set_stage(f"warmup-fair{share}")
        engine.generate_sync(
            "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        engine.mark_steady_state()
        _set_stage(f"measure-fair{share}")
        hog_prompt = "H" * min(96, engine.max_prompt_tokens - new_tokens - 8)
        t0 = time.time()
        hog_handles = []
        hog_shed = 0
        # The hog floods first — its queued cost is what the fairness
        # share caps; the well-behaved tenants' small submits follow
        # behind it, exactly the arrival order that starves them today.
        for i in range(hog_requests):
            try:
                hog_handles.append(engine.submit_generate(
                    hog_prompt + f" {i:03d}", max_new_tokens=new_tokens,
                    temperature=0.0, stop_on_eos=False, tenant="hog",
                ))
            except ErrorTooManyRequests:
                hog_shed += 1
        wb_handles: dict = {}
        for t in range(n_tenants):
            name = f"wb-{t}"
            wb_handles[name] = []
            for i in range(wb_requests):
                try:
                    wb_handles[name].append(engine.submit_generate(
                        f"tenant {name} request {i:02d}",
                        max_new_tokens=new_tokens, temperature=0.0,
                        stop_on_eos=False, tenant=name,
                    ))
                except ErrorTooManyRequests:
                    pass
        per_tenant: dict = {}
        wb_results = []
        for name, handles in wb_handles.items():
            results = [h.future.result(timeout=1800) for h in handles]
            wb_results.extend(results)
            per_tenant[name] = sum(len(r.token_ids) for r in results)
        hog_results = [h.future.result(timeout=1800) for h in hog_handles]
        per_tenant["hog"] = sum(len(r.token_ids) for r in hog_results)
        wall = time.time() - t0
        slo = engine.slo_report()
        burn = (
            slo["slos"]["ttft"]["windows"]["5m"]["burn_rate"]
            if slo.get("enabled") else 0.0
        )
        tenants_table = engine.tenant_report()["tenants"]
        _recompile_guard(engine)
        engine.stop_sync()
        tps = {
            name: round(tokens / wall, 2)
            for name, tokens in per_tenant.items()
        }
        wb_ttfts = sorted(r.ttft_s * 1e3 for r in wb_results)
        # The bench's own except-counter and the ledger's shed outcome
        # count the SAME submit-time events — report one, cross-check
        # the other.
        ledger_shed = int(
            tenants_table.get("hog", {})
            .get("requests", {}).get("shed", 0)
        )
        if ledger_shed != hog_shed:
            log(f"bench[tenant]: WARNING ledger hog sheds "
                f"({ledger_shed}) != submit-path sheds ({hog_shed})")
        out = {
            "wall_s": round(wall, 2),
            "tenant_tps": tps,
            "tenant_tps_min": min(tps.values()),
            "tenant_tps_max": max(tps.values()),
            "wb_ttft_p95_ms": round(_pct(wb_ttfts, 0.95), 2),
            "hog_shed": hog_shed,
            "slo_ttft_burn": round(burn, 4),
        }
        log(f"bench[tenant]: fair_share={share} → wb ttft_p95="
            f"{out['wb_ttft_p95_ms']}ms hog_shed={out['hog_shed']} "
            f"tps={tps} slo_ttft_burn={out['slo_ttft_burn']}")
        return out

    unfair = run(0.0)
    fair = run(fair_share)
    _set_stage("done")
    total_tps = sum(unfair["tenant_tps"].values())
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(total_tps, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(total_tps / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "tenant",
        "tenant_count": n_tenants + 1,  # N well-behaved + the hog
        "fair_share": fair_share,
        "tenant_tps_min": unfair["tenant_tps_min"],
        "tenant_tps_max": unfair["tenant_tps_max"],
        "slo_ttft_burn": unfair["slo_ttft_burn"],
        # The fairness A/B: the well-behaved tenants' TTFT with the
        # hog shed on its own budget vs sharing the pain.
        "wb_ttft_p95_unfair_ms": unfair["wb_ttft_p95_ms"],
        "wb_ttft_p95_fair_ms": fair["wb_ttft_p95_ms"],
        "hog_shed_unfair": unfair["hog_shed"],
        "hog_shed_fair": fair["hog_shed"],
        "slo_ttft_burn_fair": fair["slo_ttft_burn"],
    }), flush=True)
    os._exit(0)


def _overload_workload(on_tpu: bool) -> None:
    """BENCH_OVERLOAD_WORKLOAD=1: overload-storm A/B — a batch-class
    hog floods the queue while interactive requests arrive, with an
    aggressive TTFT SLO (BENCH_SLO_TTFT_MS=1, every request breaches)
    so the burn rate pegs immediately. The SAME storm runs twice:
    brownout off (TPU_BROWNOUT=0 behavior — everyone queues until the
    static budgets trip) then on (the ladder climbs, batch sheds first,
    interactive keeps flowing). Reports interactive goodput and TTFT
    p99 under both policies, per-class shed counts, and the highest
    ladder level reached. Self-contained: paged engine, no profile
    phase, CPU-safe."""
    from gofr_tpu.errors import ErrorTooManyRequests
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_interactive = int(os.environ.get("BENCH_REQUESTS", "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "16" if on_tpu else "8"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "2"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "256"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "32"))
    batch_requests = int(os.environ.get("BENCH_HOG_REQUESTS", "16"))
    queue_tokens = int(os.environ.get("BENCH_QUEUE_TOKENS", "512"))
    # Every request breaches a 1ms TTFT objective → the 5m burn pegs
    # at 1/error-budget from the first retirement: a deterministic
    # storm signal without waiting out real latency degradation.
    slo_ttft_ms = float(os.environ.get("BENCH_SLO_TTFT_MS", "1"))

    log(f"bench[overload]: model={model} interactive={n_interactive} "
        f"batch={batch_requests} queue_tokens={queue_tokens} "
        f"slo_ttft_ms={slo_ttft_ms}")

    def run(brownout: bool) -> dict:
        _set_stage(f"engine-init-brownout{int(brownout)}")
        engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len,
            tokenizer=ByteTokenizer(),
            window_k=int(os.environ.get("BENCH_WINDOW", "8")),
            pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
            kv_block=kv_block,
            queue_max_tokens=queue_tokens,
            slo_ttft_ms=slo_ttft_ms,
            slo_availability=0.999,
            brownout=brownout,
            # Sub-second sustain windows so the ladder climbs inside
            # the bench's storm (production defaults are 10s/30s).
            brownout_sustain_s=0.05,
            brownout_exit_sustain_s=30.0,
            brownout_max_new=max(4, new_tokens // 2),
            seed=0,
        )
        engine.start_sync()
        _set_stage(f"warmup-brownout{int(brownout)}")
        engine.generate_sync(
            "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        engine.mark_steady_state()
        _set_stage(f"measure-brownout{int(brownout)}")
        batch_prompt = "B" * min(96, engine.max_prompt_tokens - new_tokens - 8)
        shed = {"batch": 0, "interactive": 0}
        max_level = 0
        t0 = time.time()
        handles = []
        interactive_results = []
        # Interleave: batch floods ~2:1 against interactive arrivals,
        # with a breather between waves so the scheduler retires work
        # (retirements feed the burn; the ladder needs a few windows).
        waves = max(n_interactive, 1)
        for w in range(waves):
            for i in range(max(1, batch_requests // waves)):
                try:
                    handles.append(engine.submit_generate(
                        batch_prompt + f" {w:02d}{i:02d}",
                        max_new_tokens=new_tokens, temperature=0.0,
                        stop_on_eos=False, slo_class="batch",
                        tenant="hog",
                    ))
                except ErrorTooManyRequests:
                    shed["batch"] += 1
            try:
                interactive_results.append(engine.generate_sync(
                    f"interactive {w:02d}", max_new_tokens=new_tokens,
                    temperature=0.0, stop_on_eos=False,
                    slo_class="interactive", timeout=1800,
                ))
            except ErrorTooManyRequests:
                shed["interactive"] += 1
            max_level = max(max_level, engine.brownout_level() or 0)
        for h in handles:
            h.future.result(timeout=1800)
        wall = time.time() - t0
        goodput = sum(
            len(r.token_ids) for r in interactive_results
        ) / wall
        ttfts = sorted(r.ttft_s * 1e3 for r in interactive_results)
        bc = engine._brownout
        if bc is not None:
            shed["batch"] = max(shed["batch"], bc.shed_count("batch"))
            shed["interactive"] = max(
                shed["interactive"], bc.shed_count("interactive")
            )
        _recompile_guard(engine)
        engine.stop_sync()
        out = {
            "wall_s": round(wall, 2),
            "interactive_goodput": round(goodput, 2),
            "ttft_p99_ms": round(_pct(ttfts, 0.99), 2) if ttfts else -1.0,
            "shed_batch": shed["batch"],
            "shed_interactive": shed["interactive"],
            "max_level": max_level,
        }
        log(f"bench[overload]: brownout={brownout} → goodput="
            f"{out['interactive_goodput']} tok/s ttft_p99="
            f"{out['ttft_p99_ms']}ms shed={shed} max_level={max_level}")
        return out

    off = run(False)
    on = run(True)
    _set_stage("done")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": on["interactive_goodput"],
        "unit": "tok/s/chip",
        "vs_baseline": round(on["interactive_goodput"] / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "overload",
        # The brownout A/B: what graded degradation buys interactive
        # traffic during a storm, and who paid for it.
        "interactive_goodput_off": off["interactive_goodput"],
        "interactive_goodput_on": on["interactive_goodput"],
        "ttft_p99_off_ms": off["ttft_p99_ms"],
        "ttft_p99_on_ms": on["ttft_p99_ms"],
        "shed_batch_total": off["shed_batch"] + on["shed_batch"],
        "shed_interactive_total": (
            off["shed_interactive"] + on["shed_interactive"]
        ),
        "shed_batch_on": on["shed_batch"],
        "shed_interactive_on": on["shed_interactive"],
        "max_brownout_level": on["max_level"],
    }), flush=True)
    os._exit(0)


def _tp_workload(on_tpu: bool) -> None:
    """BENCH_TP_WORKLOAD=1: the GSPMD-sharded serving A/B — one
    synchronized greedy burst served by a tp=1 engine, then the SAME
    burst by a tp=2 engine (params Megatron-sharded, KV head axis
    sharded). Greedy streams must be TOKEN-IDENTICAL between the two
    (the tp-invariance contract the sharded-serving suite pins); a
    mismatch fails the row rather than reporting a wrong-answer
    speedup. On CPU virtual devices the collective overhead dominates,
    so the row is degraded / NOT comparable — it captures the sharded
    engine's step-time trajectory until a real multi-chip TPU window
    lands."""
    import jax

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_requests = int(os.environ.get("BENCH_REQUESTS", "16" if on_tpu else "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "32" if on_tpu else "8"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "8"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "1024" if on_tpu else "256"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "0"))
    devices = jax.devices()
    if len(devices) < 2:
        log(f"bench[tp]: only {len(devices)} device(s) visible — "
            f"cannot A/B tp=2; rerun with 2+ chips or the CPU backend")
        os._exit(4)
    log(f"bench[tp]: model={model} requests={n_requests} "
        f"new_tokens={new_tokens} slots={n_slots} devices={len(devices)}")

    prompt = "The quick brown fox jumps over the lazy dog. " * 3

    def run(tp: int) -> tuple[float, list]:
        _set_stage(f"engine-init-tp{tp}")
        engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len,
            tokenizer=ByteTokenizer(),
            window_k=int(os.environ.get("BENCH_WINDOW", "8")),
            pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
            kv_block=kv_block,
            tp=tp, devices=devices[:tp] if tp > 1 else None, seed=0,
        )
        engine.start_sync()
        _set_stage(f"warmup-tp{tp}")
        engine.generate_sync(
            prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False
        )
        _set_stage(f"measure-tp{tp}")
        t0 = time.time()
        reqs = [
            engine.submit_generate(
                prompt, max_new_tokens=new_tokens, temperature=0.0,
                stop_on_eos=False,
            )
            for _ in range(n_requests)
        ]
        results = [r.future.result(timeout=1800) for r in reqs]
        wall = time.time() - t0
        toks = sum(len(r.token_ids) for r in results)
        engine.stop_sync()
        log(f"bench[tp]: tp={tp} → {toks} tokens in {wall:.2f}s "
            f"({toks / wall:.1f} tok/s)")
        return toks / wall, [r.token_ids for r in results]

    tp1_tps, streams1 = run(1)
    tp2_tps, streams2 = run(2)
    if streams1 != streams2:
        log("bench[tp]: TOKEN MISMATCH between tp=1 and tp=2 — the "
            "tp-invariance contract is broken; refusing to report a "
            "wrong-answer speedup")
        os._exit(5)
    _set_stage("done")
    platform = "tpu" if on_tpu else "cpu"
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tp2_tps / 2, 2),  # per-CHIP: tp=2 spans two
        "unit": "tok/s/chip",
        "vs_baseline": round(tp2_tps / 2 / 1000.0, 4),
        "platform": platform,
        # CPU virtual devices measure gloo-collective overhead, not ICI:
        # degraded rows never impersonate TPU numbers.
        "degraded": not on_tpu,
        "model": model,
        "workload": "tp_ab",
        "tp1_tps": round(tp1_tps, 2),
        "tp2_tps": round(tp2_tps, 2),
        "tp_speedup": round(tp2_tps / tp1_tps, 3) if tp1_tps else None,
        "token_identical": True,
    }), flush=True)
    os._exit(0)


def _tier_workload(on_tpu: bool) -> None:
    """BENCH_TIER_WORKLOAD=1: disaggregated-tier transfer-leg A/B — the
    SAME prefill-heavy burst served through a 1-prefill + 1-decode
    in-proc pool with the transfer leg pinned to host-bounce, then to
    the device leg. One JSON line carries per-leg transfer latency
    (p50/p95 ms, from the request timelines' tpu.transfer hops), the
    decode-tier cold TTFT per leg, streamed-token identity across legs,
    and the pool's tier_transfers_total{leg,result} counters. The
    acceptance bar: the device leg's transfer p50 strictly below the
    host bounce's on the same workload (CPU fallback rows are marked
    degraded as usual — PCIe/ICI asymmetry only exists on real
    hardware, but the zero-host-copy path must already win on CPU
    because it skips two full plane materializations)."""
    import random

    from gofr_tpu.metrics import new_metrics_manager
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer
    from gofr_tpu.service.replica_pool import EngineReplica, ReplicaPool

    model = os.environ.get("BENCH_MODEL", "llama-tiny")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "8"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "32"))
    prompt_tokens = int(os.environ.get("BENCH_TIER_PROMPT", "96"))

    metrics = new_metrics_manager()
    metrics.new_counter("app_tpu_tier_transfers_total")
    metrics.new_counter("app_tpu_tier_transfer_bytes_total")
    metrics.new_histogram("app_tpu_tier_transfer_seconds")
    metrics.new_gauge("app_tpu_tier_mode")

    log(f"bench[tier]: model={model} requests={n_requests}/leg "
        f"prompt={prompt_tokens}tok kv_block={kv_block}")
    _set_stage("engine-init")

    def mk():
        eng = InferenceEngine(
            model, n_slots=4, max_len=256, window_k=4, pipeline_depth=1,
            prefill_chunk=32, kv_block=kv_block, auto_prefix=True,
            tokenizer=ByteTokenizer(),
        )
        eng.start_sync()
        return eng

    pf, dc = mk(), mk()
    pool = ReplicaPool(
        [
            EngineReplica("pf", pf, role="prefill"),
            EngineReplica("dc", dc, role="decode"),
        ],
        probe_interval_s=0, hedge_delay_s=300.0,
        rng=random.Random(7), metrics=metrics,
    )

    _SALTS = {
        "host": 0, "device": 101, "dma": 211, "source": 271,
        "warm-host": 53, "warm-device": 157, "warm-dma": 59,
    }

    def prompt(leg: str, i: int) -> list:
        # Distinct per (leg, request): every transfer ships cold
        # content — a collision would dedupe against the decode tier's
        # radix and skip the very leg being measured.
        base = [2 + (i * 7 + _SALTS[leg]) % 200]
        return (base * prompt_tokens)[:prompt_tokens - 1] + [3 + i]

    def run_leg(leg: str) -> dict:
        pool.transfer_leg = leg
        reqs = [
            pool.submit_generate(
                prompt(leg, i), max_new_tokens=new_tokens,
                temperature=0.0,
            )
            for i in range(n_requests)
        ]
        results = [r.future.result(timeout=600) for r in reqs]
        hops = [
            hop
            for r in reqs if r.timeline is not None
            for hop in r.timeline.transfers
        ]
        xfer_ms = sorted(
            (end - start) * 1e3
            for _, _, start, end, result, hop_leg in hops
            if result == "ok" and hop_leg == leg
        )
        ttfts = sorted(r.ttft_s * 1e3 for r in results)
        return {
            "tokens": [list(r.token_ids) for r in results],
            f"transfer_ms_{leg}": {
                "p50": round(_pct(xfer_ms, 0.50), 3),
                "p95": round(_pct(xfer_ms, 0.95), 3),
            },
            f"cold_ttft_{leg}_p50_ms": round(_pct(ttfts, 0.50), 2),
            f"transfers_{leg}": len(xfer_ms),
        }

    def run_source() -> dict:
        """The remote-source pull seam's data path, in-proc: the
        prefill tier exports cached blocks (``export_cached``), stages
        them on the loopback transfer server, the decode tier redeems
        the claim ticket (``dma_fetch``) and applies it
        (``import_payload``) — the full ``/ops/tier-export`` cycle
        minus the HTTP control round-trip."""
        from gofr_tpu.service.dma import dma_fetch, get_transfer_server

        times, hits = [], 0
        for i in range(n_requests):
            ids = prompt("source", i)
            # Populate the prefill tier's radix the way a real source
            # has it populated: by serving the request.
            pf.generate_sync(ids, max_new_tokens=2, temperature=0.0)
            t0 = time.time()
            payload = pf.export_cached(ids, timeout_s=10.0)
            if payload is None:
                continue
            handle = get_transfer_server().offer(payload, src="pf")
            fetched = dma_fetch(
                handle, connect_timeout_s=2.0, read_timeout_s=10.0,
            )
            if dc.import_payload(fetched, wait_s=5.0) == "imported":
                hits += 1
            times.append((time.time() - t0) * 1e3)
        ms = sorted(times)
        return {
            "source_pull_ms": {
                "p50": round(_pct(ms, 0.50), 3),
                "p95": round(_pct(ms, 0.95), 3),
            },
            "source_pulls": len(ms),
            "source_hits": hits,
        }

    _set_stage("warmup")
    # One transfer per leg compiles extract/move (device) and the
    # insert path (host) BEFORE the fence — a steady-state transfer
    # must never hide a recompile (exit 6 below if one does). The dma
    # leg's warm run also brings up the loopback transfer server.
    for warm_leg in ("host", "device", "dma"):
        pool.transfer_leg = warm_leg
        pool.generate_sync(
            prompt(f"warm-{warm_leg}", 0), max_new_tokens=new_tokens,
            temperature=0.0, timeout=600,
        )
    pf.mark_steady_state()
    dc.mark_steady_state()

    _set_stage("measure")
    t0 = time.time()
    host = run_leg("host")
    device = run_leg("device")
    dma = run_leg("dma")
    source = run_source()
    wall = time.time() - t0
    # Prompts differ per leg by design (each leg must transfer COLD
    # content); the legs-move-bytes-not-meaning identity contract is
    # pinned in CI (tests/test_tier_d2d.py) against a fused reference.
    host.pop("tokens")
    device.pop("tokens")
    dma.pop("tokens")
    counters = {}
    for inst in metrics.instruments():
        if inst.name == "app_tpu_tier_transfers_total":
            for key, value in inst.collect().items():
                counters["|".join("=".join(p) for p in key)] = value
    device_fields = _device_resource_fields(dc)
    loop_fields = _loop_fields(dc)
    for eng in (pf, dc):
        _recompile_guard(eng)
    host_p50 = host["transfer_ms_host"]["p50"]
    dev_p50 = device["transfer_ms_device"]["p50"]
    dma_p50 = dma["transfer_ms_dma"]["p50"]
    log(f"bench[tier]: transfer p50 host={host_p50}ms "
        f"device={dev_p50}ms dma={dma_p50}ms "
        f"source_pull p50={source['source_pull_ms']['p50']}ms "
        f"({wall:.2f}s total); device_wins={dev_p50 < host_p50}")
    pf.close()
    dc.close()
    _set_stage("done")
    row = {
        "metric": "tier_transfer_ms_p50_device",
        "value": dev_p50,
        "unit": "ms",
        "vs_baseline": round(
            host_p50 / dev_p50, 3
        ) if dev_p50 else None,
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "tier_legs",
        **{k: v for k, v in host.items()},
        **{k: v for k, v in device.items()},
        **{k: v for k, v in dma.items()},
        **source,
        "device_leg_faster": bool(dev_p50 < host_p50),
        "tier_transfers_total": counters,
        **device_fields,
        **loop_fields,
    }
    row.update(_trajectory_fields(row))
    print(json.dumps(row), flush=True)
    os._exit(0)


def _spec_workload(on_tpu: bool) -> None:
    """BENCH_SPEC_WORKLOAD=1: n-gram speculation A/B — the SAME
    repeated-text burst (the prompt-lookup-friendly shape: the
    continuation keeps re-walking substrings of the prompt) served by a
    spec=0 engine and a spec=G (BENCH_SPEC_G=2) engine. Since the
    exact-verify redesign (ISSUE 20) the spec window runs the literal
    decode-step program per candidate position, so identity is
    ENFORCED, not reported: any stream divergence vs spec=0 exits 5
    (the BENCH_TP_WORKLOAD idiom) — a diverged run is a correctness
    bug, never a number worth publishing. The JSON line carries both
    throughputs, the speedup, the acceptance series summary
    (mean + ``acc_p50``/``acc_p95`` over per-window tokens-per-step),
    ``host_overhead_ratio_{off,on}`` (the loop profiler's
    host-bookkeeping share — the metric the default-on gate reads,
    since exact verify wins by DISPATCH amortization, not compute),
    the composed ``default_on_gate`` verdict (tok/s strictly up AND
    host overhead not regressing — exactly when
    ``TPU_SPEC_TOKENS=auto`` resolves ON), and the run-over-run
    trajectory vs the newest committed BENCH_*.json row."""
    from gofr_tpu.metrics import new_metrics_manager
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get("BENCH_MODEL", "llama-tiny")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "8"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "32"))
    spec_g = int(os.environ.get("BENCH_SPEC_G", "2"))
    # Repeated text: "abcabcabc…" with a per-request rotation — the
    # n-gram draft's best case, and exactly the retrieval/multi-turn
    # shape the prefix cache already targets.
    prompts = [
        ("abcdefgh"[i % 4:] + "abcdefgh" * 12)[:64]
        for i in range(n_requests)
    ]

    log(f"bench[spec]: model={model} requests={n_requests} "
        f"new_tokens={new_tokens} spec_g={spec_g}")
    _set_stage("engine-init")

    def serve(spec_tokens: int) -> tuple:
        metrics = new_metrics_manager()
        metrics.new_histogram("app_tpu_spec_tokens_per_step")
        # Raw acceptance series alongside the bucketed histogram: the
        # scheduler records one tokens-per-live-step value per window;
        # percentiles need the raw samples, not bucket edges.
        acc_series: list = []
        inst = {
            i.name: i for i in metrics.instruments()
        }["app_tpu_spec_tokens_per_step"]
        inner_record = inst.record

        def recording(value, labels):
            acc_series.append(float(value))
            inner_record(value, labels)

        inst.record = recording  # type: ignore[method-assign]
        eng = InferenceEngine(
            model, n_slots=8, max_len=256, window_k=4,
            tokenizer=ByteTokenizer(), spec_tokens=spec_tokens,
            metrics=metrics,
        )
        eng.start_sync()
        eng.generate_sync(
            "warm" * 4, max_new_tokens=2, temperature=0.0,
            stop_on_eos=False,
        )
        eng.mark_steady_state()
        t0 = time.time()
        reqs = [
            eng.submit_generate(
                p, max_new_tokens=new_tokens, temperature=0.0,
                stop_on_eos=False,
            )
            for p in prompts
        ]
        results = [r.future.result(timeout=600) for r in reqs]
        wall = time.time() - t0
        _recompile_guard(eng)
        loop = _loop_fields(eng)
        device = _device_resource_fields(eng)
        eng.close()
        total = sum(len(r.token_ids) for r in results)
        return (
            total / wall,
            sorted(acc_series),
            [list(r.token_ids) for r in results],
            loop,
            device,
        )

    _set_stage("measure")
    plain_tps, _, plain_tokens, loop_off, _ = serve(0)
    spec_tps, acc_series, spec_tokens_out, loop_on, device_on = serve(spec_g)
    diverged = sum(
        1 for a, b in zip(plain_tokens, spec_tokens_out) if a != b
    )
    acceptance = (
        sum(acc_series) / len(acc_series) if acc_series else None
    )
    log(f"bench[spec]: plain={plain_tps:.1f} tok/s "
        f"spec={spec_tps:.1f} tok/s "
        f"acceptance={acceptance if acceptance is None else round(acceptance, 3)} "
        f"diverged={diverged}/{len(plain_tokens)}")
    if diverged:
        # The exact-verify contract is the whole point of default-on:
        # a diverged stream means the verify path stopped reproducing
        # decode numerics. Refuse the row (exit 5, like tp identity).
        log(f"bench[spec]: {diverged}/{len(plain_tokens)} STREAM(S) "
            "DIVERGED from spec=0 — the exact-verify contract is "
            "broken; refusing to report a wrong-answer speedup")
        os._exit(5)
    host_off = loop_off.get("host_overhead_ratio")
    host_on = loop_on.get("host_overhead_ratio")
    tok_s_up = spec_tps > plain_tps
    # "Not regressing": within 5% relative (plus epsilon absolute for
    # near-zero ratios) of the spec=0 run's host-bookkeeping share.
    host_flat = (
        host_off is None or host_on is None
        or host_on <= host_off * 1.05 + 0.005
    )
    _set_stage("done")
    row = {
        "metric": "spec_decode_tokens_per_sec",
        "value": round(spec_tps, 2),
        "unit": "tok/s",
        "vs_baseline": round(spec_tps / plain_tps, 3) if plain_tps else None,
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "spec_ab",
        "spec_g": spec_g,
        "plain_tps": round(plain_tps, 2),
        "spec_tps": round(spec_tps, 2),
        "spec_speedup": round(spec_tps / plain_tps, 3) if plain_tps else None,
        "spec_tokens_per_step": (
            round(acceptance, 3) if acceptance is not None else None
        ),
        "acc_p50": round(_pct(acc_series, 0.50), 3),
        "acc_p95": round(_pct(acc_series, 0.95), 3),
        "spec_identical": True,  # enforced above: divergence exits 5
        "diverged_requests": diverged,
        "host_overhead_ratio_off": host_off,
        "host_overhead_ratio_on": host_on,
        # The two-metric verdict the TPU_SPEC_TOKENS=auto default rides
        # on: flip on only where speculation pays on THIS platform.
        "default_on_gate": {
            "tok_s_up": tok_s_up,
            "host_overhead_flat": host_flat,
            "pass": bool(tok_s_up and host_flat),
        },
        **device_on,
    }
    row.update(_trajectory_fields(row))
    print(json.dumps(row), flush=True)
    os._exit(0)


def _control_workload(on_tpu: bool) -> None:
    """BENCH_CONTROL_WORKLOAD=1: control-plane A/B — a diurnal ramp
    (one hog tenant's flood swells wave by wave, then recedes) over a
    small queue while well-behaved tenants submit steadily, run with
    the control plane off then on (``TPU_CONTROL_PLANE``). With
    ``slo_availability`` armed, the hog's admission sheds burn ITS
    availability SLO alone, so the per-tenant ladder climbs for the hog
    while everyone else stays at L0 — the isolation the A/B prices.
    Reports per-tenant goodput min/max under both policies, the hog's
    highest ladder level, the predictive loop's scale LEAD TIME (first
    scale-pressure assertion vs the queue actually reaching the
    reactive depth), and the control plane's degraded-signal and
    eval-error counts. Self-contained: paged engine, no profile phase,
    CPU-safe."""
    from gofr_tpu.errors import ErrorTooManyRequests
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_tenants = int(os.environ.get("BENCH_TENANTS", "3"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "16" if on_tpu else "8"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "2"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "256"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "32"))
    queue_tokens = int(os.environ.get("BENCH_QUEUE_TOKENS", "256"))
    # The hog's per-wave submit count is weight x unit over this
    # diurnal shape: quiet shoulders, a rising edge for the predictive
    # loop's trend fit, a saturating plateau, then the ebb that lets
    # the ladder's exit hysteresis run.
    ramp = (0, 1, 2, 4, 4, 2, 1, 0)
    hog_unit = int(os.environ.get("BENCH_HOG_UNIT", "3"))
    predict_depth = float(os.environ.get("BENCH_PREDICT_DEPTH", "6"))

    log(f"bench[control]: model={model} tenants={n_tenants} "
        f"hog_unit={hog_unit} queue_tokens={queue_tokens} "
        f"predict_depth={predict_depth}")

    def run(control: bool) -> dict:
        _set_stage(f"engine-init-control{int(control)}")
        engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len,
            tokenizer=ByteTokenizer(),
            window_k=int(os.environ.get("BENCH_WINDOW", "8")),
            pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
            kv_block=kv_block,
            # Small enough that the plateau's flood sheds at admission:
            # those sheds are what burn the hog's availability SLO.
            queue_max_tokens=queue_tokens,
            slo_availability=0.999,
            control_plane=control,
            # Sub-second sustain windows so the per-tenant ladder
            # climbs inside the bench (production defaults are 10s).
            control_tenant_sustain_s=0.05,
            control_tenant_exit_sustain_s=30.0,
            # Short trend window/horizon matched to wave cadence, and
            # no hold-down replay: the lead-time number should reflect
            # the FIRST assertion.
            control_predict_window_s=30.0,
            control_predict_horizon_s=5.0,
            control_predict_depth=predict_depth,
            seed=0,
        )
        engine.start_sync()
        _set_stage(f"warmup-control{int(control)}")
        engine.generate_sync(
            "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        engine.mark_steady_state()
        _set_stage(f"measure-control{int(control)}")
        hog_prompt = "H" * min(96, engine.max_prompt_tokens - new_tokens - 8)
        t0 = time.time()
        hog_handles = []
        hog_shed = 0
        wb_shed = 0
        wb_results: dict = {name: [] for name in
                            (f"wb-{t}" for t in range(n_tenants))}
        max_level = 0
        t_pressure = None  # first control scale-pressure assertion
        t_reactive = None  # queue first reaches the reactive depth
        for w, weight in enumerate(ramp):
            for i in range(weight * hog_unit):
                try:
                    hog_handles.append(engine.submit_generate(
                        hog_prompt + f" {w:02d}{i:02d}",
                        max_new_tokens=new_tokens, temperature=0.0,
                        stop_on_eos=False, tenant="hog",
                    ))
                except ErrorTooManyRequests:
                    hog_shed += 1
            # The scale-lead-time probe: the predictive loop should
            # assert pressure on the rising edge's TREND, before the
            # depth itself crosses the reactive threshold.
            depth = float(engine._pending.qsize())
            if t_reactive is None and depth >= predict_depth:
                t_reactive = time.time() - t0
            if control and t_pressure is None:
                if engine.control_scale_pressure() == 1:
                    t_pressure = time.time() - t0
            cp = engine._control
            if cp is not None:
                max_level = max(max_level, cp.tenant_level("hog"))
            # One synchronous interactive request per well-behaved
            # tenant per wave: retirements pace the waves and feed the
            # per-tenant burn windows.
            for name in wb_results:
                try:
                    wb_results[name].append(engine.generate_sync(
                        f"tenant {name} wave {w:02d}",
                        max_new_tokens=new_tokens, temperature=0.0,
                        stop_on_eos=False, tenant=name, timeout=1800,
                    ))
                except ErrorTooManyRequests:
                    wb_shed += 1
        for h in hog_handles:
            try:
                h.future.result(timeout=1800)
            except ErrorTooManyRequests:
                # L3 fair-share shed can fail an already-queued hog
                # request at admission re-check; that is the ladder
                # working, not a bench failure.
                hog_shed += 1
        wall = time.time() - t0
        report = engine.control_report()
        _recompile_guard(engine)
        engine.stop_sync()
        wb_tps = {
            name: round(sum(len(r.token_ids) for r in rs) / wall, 2)
            for name, rs in wb_results.items()
        }
        degraded = sorted(
            name for name, s in report.get("signals", {}).items()
            if s.get("status") != "ok"
        )
        out = {
            "wall_s": round(wall, 2),
            "wb_goodput_min": min(wb_tps.values()),
            "wb_goodput_max": max(wb_tps.values()),
            "hog_shed": hog_shed,
            "wb_shed": wb_shed,
            "max_tenant_level": max_level,
            "scale_lead_s": (
                round(t_reactive - t_pressure, 3)
                if t_pressure is not None and t_reactive is not None
                and t_reactive > t_pressure else None
            ),
            "pressure_asserted": t_pressure is not None,
            "degraded_signals": len(degraded),
            "control_passes": int(report.get("passes", 0)),
            "control_eval_errors": int(report.get("eval_errors", 0)),
        }
        log(f"bench[control]: control={control} → wb goodput "
            f"[{out['wb_goodput_min']}, {out['wb_goodput_max']}] tok/s "
            f"hog_shed={hog_shed} wb_shed={wb_shed} "
            f"max_tenant_level={max_level} "
            f"scale_lead_s={out['scale_lead_s']} degraded={degraded}")
        return out

    off = run(False)
    on = run(True)
    _set_stage("done")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": on["wb_goodput_min"],
        "unit": "tok/s/chip",
        "vs_baseline": round(on["wb_goodput_min"] / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "control",
        "tenant_count": n_tenants + 1,  # N well-behaved + the hog
        # The control A/B: does the ladder keep the hog's storm off
        # the well-behaved tenants' goodput floor?
        "wb_goodput_min_off": off["wb_goodput_min"],
        "wb_goodput_min_on": on["wb_goodput_min"],
        "wb_goodput_max_off": off["wb_goodput_max"],
        "wb_goodput_max_on": on["wb_goodput_max"],
        "hog_shed_off": off["hog_shed"],
        "hog_shed_on": on["hog_shed"],
        "wb_shed_off": off["wb_shed"],
        "wb_shed_on": on["wb_shed"],
        "max_tenant_level": on["max_tenant_level"],
        "scale_lead_s": on["scale_lead_s"],
        "pressure_asserted": on["pressure_asserted"],
        "degraded_signals": on["degraded_signals"],
        "control_passes": on["control_passes"],
        "control_eval_errors": on["control_eval_errors"],
    }), flush=True)
    os._exit(0)


def _async_workload(on_tpu: bool) -> None:
    """BENCH_ASYNC_WORKLOAD=1: durable async-serving idle-soak A/B
    (serving/async_serving.py) — the same interactive trickle measured
    with the async plane off, then on against a request-topic backlog.
    Poison messages ride along so the redelivery/dead-letter machinery
    is priced too, not just the happy path. The claim the A/B prices:
    async (batch-class) work soaks the idle capacity between
    interactive arrivals WITHOUT moving interactive TTFT — the p95
    pair off/on is the headline, async_tps is what that idle capacity
    bought, redelivered/dead_lettered prove the contract machinery ran.
    Self-contained: paged engine, in-memory broker, CPU-safe."""
    from gofr_tpu.pubsub import InMemoryBroker
    from gofr_tpu.serving.async_serving import AsyncServingPlane
    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer
    from gofr_tpu.service.options import RetryConfig

    model = os.environ.get(
        "BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny"
    )
    n_interactive = int(os.environ.get("BENCH_REQUESTS", "12"))
    n_async = int(os.environ.get("BENCH_ASYNC_BACKLOG", "24"))
    n_poison = int(os.environ.get("BENCH_ASYNC_POISON", "2"))
    new_tokens = int(os.environ.get(
        "BENCH_NEW_TOKENS", "16" if on_tpu else "8"
    ))
    n_slots = int(os.environ.get("BENCH_SLOTS", "4"))
    # The trickle's inter-arrival gap IS the idle capacity async soaks.
    arrival_s = float(os.environ.get("BENCH_ARRIVAL_MS", "150")) / 1000.0

    log(f"bench[async]: model={model} interactive={n_interactive} "
        f"backlog={n_async}+{n_poison} poison arrival_ms="
        f"{arrival_s * 1000:.0f}")

    def run(async_on: bool) -> dict:
        _set_stage(f"engine-init-async{int(async_on)}")
        engine = InferenceEngine(
            model, n_slots=n_slots,
            max_len=int(os.environ.get("BENCH_MAX_LEN", "256")),
            tokenizer=ByteTokenizer(),
            window_k=int(os.environ.get("BENCH_WINDOW", "8")),
            pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
            kv_block=int(os.environ.get("BENCH_KV_BLOCK", "32")),
            seed=0,
        )
        engine.start_sync()
        _set_stage(f"warmup-async{int(async_on)}")
        engine.generate_sync(
            "w" * 8, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        engine.mark_steady_state()
        plane = None
        if async_on:
            broker = InMemoryBroker()
            plane = AsyncServingPlane(
                engine, broker,
                redelivery_max=2, lease_s=60.0, max_inflight=n_slots,
                # Fast backoff so poison reaches the DLQ inside the
                # bench window (production default is 1s base).
                retry=RetryConfig(
                    backoff_s=0.05, jitter=0.5, max_backoff_s=0.5
                ),
                poll_s=0.005,
            )
            for i in range(n_async):
                broker.publish(plane.request_topic, json.dumps({
                    "prompt": f"async soak {i:03d} " + "a" * 24,
                    "max_new_tokens": new_tokens,
                    "temperature": 0.0, "stop_on_eos": False,
                }))
            for i in range(n_poison):
                broker.publish(plane.request_topic, f"poison {i}")
            plane.start()
        _set_stage(f"measure-async{int(async_on)}")
        t0 = time.time()
        ttfts_ms = []
        for i in range(n_interactive):
            r = engine.generate_sync(
                f"interactive trickle {i:03d}",
                max_new_tokens=new_tokens, temperature=0.0,
                stop_on_eos=False, slo_class="interactive", timeout=1800,
            )
            ttfts_ms.append(r.ttft_s * 1000.0)
            time.sleep(arrival_s)
        async_tokens = 0
        replies = 0
        counters: dict = {}
        if plane is not None:
            # Soak until the backlog fully drains (replied or parked).
            drain_deadline = time.time() + float(
                os.environ.get("BENCH_ASYNC_DRAIN_S", "300")
            )
            while (
                time.time() < drain_deadline
                and plane.broker.size(plane.request_topic) > 0
            ):
                time.sleep(0.02)
            wall = time.time() - t0
            for m in plane.broker.peek_all(plane.reply_topic):
                replies += 1
                async_tokens += len(
                    json.loads(m.value).get("token_ids") or []
                )
            counters = dict(plane.counters)
            plane.stop(drain_s=10.0)
        else:
            wall = time.time() - t0
        ttfts_ms.sort()
        p95 = ttfts_ms[min(len(ttfts_ms) - 1, int(0.95 * len(ttfts_ms)))]
        _recompile_guard(engine)
        engine.stop_sync()
        out = {
            "wall_s": round(wall, 2),
            "ttft_p95_ms": round(p95, 2),
            "async_tps": round(async_tokens / wall, 2) if wall > 0 else 0.0,
            "async_replies": replies,
            "redelivered": int(counters.get("redelivered", 0)),
            "dead_lettered": int(counters.get("dead_lettered", 0)),
        }
        log(f"bench[async]: async={async_on} → ttft_p95="
            f"{out['ttft_p95_ms']}ms async_tps={out['async_tps']} "
            f"replies={replies} redelivered={out['redelivered']} "
            f"dead_lettered={out['dead_lettered']}")
        return out

    off = run(False)
    on = run(True)
    _set_stage("done")
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": on["async_tps"],
        "unit": "tok/s/chip",
        "vs_baseline": round(on["async_tps"] / 1000.0, 4),
        "platform": "tpu" if on_tpu else "cpu",
        "degraded": not on_tpu,
        "model": model,
        "workload": "async",
        # The idle-soak A/B: async throughput bought from idle capacity,
        # priced against the interactive-TTFT pair it must not move.
        "async_tps": on["async_tps"],
        "interactive_ttft_p95_off_ms": off["ttft_p95_ms"],
        "interactive_ttft_p95_on_ms": on["ttft_p95_ms"],
        "redelivered": on["redelivered"],
        "dead_lettered": on["dead_lettered"],
        "async_replies": on["async_replies"],
        "async_backlog": n_async + n_poison,
        "interactive_requests": n_interactive,
    }), flush=True)
    os._exit(0)


def main() -> None:
    # Whole-run watchdog (round-2 lesson: the old init-only watchdog
    # released after jax.devices(), then engine-init remote compiles hung
    # ~26 min unbounded). Any stage stall past its deadline — or the whole
    # child past BENCH_CHILD_WALL — exits 3 with the stage named, so the
    # parent retries in minutes and a timeout tail says where it hung.
    import threading

    # The tp A/B needs ≥2 devices; on the CPU backend force virtual
    # devices BEFORE jax initializes (the tests/conftest.py trick).
    if (
        os.environ.get("BENCH_TP_WORKLOAD", "") in ("1", "true", "yes")
        and os.environ.get("JAX_PLATFORMS", "") == "cpu"
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()

    t_start = time.time()
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    stage_deadlines = {"jax-init": init_timeout}

    # Deadline bound AT CREATION (default arg), not late-bound from a
    # function-local — a later `wall = ...` elapsed-time assignment in
    # main() must not be able to rebind the watchdog's budget (that exact
    # bug killed every campaign-1 run at the unloaded-ttft stage).
    def _watchdog(wall=float(os.environ.get("BENCH_CHILD_WALL", "0"))) -> None:
        last_beat = 0.0
        while True:
            time.sleep(5)
            now = time.time()
            stage, since = _STAGE[0], now - _STAGE[1]
            if stage == "done":
                return
            if wall > 0 and now - t_start > wall:
                log(f"bench: child wall clock exceeded {wall:.0f}s "
                    f"(stage={stage}, {since:.0f}s in) — exiting for retry")
                os._exit(3)
            limit = stage_deadlines.get(stage)
            if limit is not None and since > limit:
                log(f"bench: stage {stage} exceeded {limit:.0f}s — "
                    f"relay wedged, exiting for retry")
                os._exit(3)
            if now - last_beat > 60:
                log(f"bench: heartbeat stage={stage} ({since:.0f}s in, "
                    f"{now - t_start:.0f}s total)")
                last_beat = now

    threading.Thread(target=_watchdog, daemon=True).start()
    _set_stage("jax-init")
    import jax

    platform = jax.devices()[0].platform
    _set_stage("config")
    on_tpu = platform == "tpu"
    if os.environ.get("BENCH_PREFIX_WORKLOAD", "") in ("1", "true", "yes"):
        _prefix_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_TP_WORKLOAD", "") in ("1", "true", "yes"):
        _tp_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_LOOP_WORKLOAD", "") in ("1", "true", "yes"):
        _loop_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_TENANT_WORKLOAD", "") in ("1", "true", "yes"):
        _tenant_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_OVERLOAD_WORKLOAD", "") in ("1", "true", "yes"):
        _overload_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_TIER_WORKLOAD", "") in ("1", "true", "yes"):
        _tier_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_SPEC_WORKLOAD", "") in ("1", "true", "yes"):
        _spec_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_CONTROL_WORKLOAD", "") in ("1", "true", "yes"):
        _control_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    if os.environ.get("BENCH_ASYNC_WORKLOAD", "") in ("1", "true", "yes"):
        _async_workload(on_tpu)
        return  # unreachable (os._exit) — keeps the control flow obvious
    model = os.environ.get("BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "32"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "1024"))
    quant = os.environ.get("BENCH_QUANT", "int8" if on_tpu else "")
    if quant.lower() in ("none", "0"):
        quant = ""
    kv_quant = os.environ.get("BENCH_KV_QUANT", "")
    if kv_quant.lower() in ("none", "0"):
        kv_quant = ""
    spec_tokens = int(os.environ.get("BENCH_SPEC", "0"))
    kv_block = int(os.environ.get("BENCH_KV_BLOCK", "0"))
    # TPU default: mega windows ON (m=8) — the dispatch-RTT amortizer is
    # the production throughput configuration; BENCH_MEGA=0 restores the
    # streaming-granularity pipelined mode (the pre-r4 campaign rows).
    mega = int(os.environ.get("BENCH_MEGA", "8" if on_tpu else "0"))
    # Multi-LoRA workload: BENCH_LORA=N loads N random rank-BENCH_LORA_RANK
    # adapters and assigns requests round-robin over (base + adapters) —
    # measures the per-slot gather + rank-einsum cost of heterogeneous
    # adapter batches against the same config with BENCH_LORA=0.
    n_lora = int(os.environ.get("BENCH_LORA", "0"))
    lora_rank = int(os.environ.get("BENCH_LORA_RANK", "16"))

    log(f"bench: platform={platform} model={model} requests={n_requests} "
        f"new_tokens={new_tokens} slots={n_slots} quant={quant or 'bf16'} "
        f"kv_quant={kv_quant or 'bf16'} spec={spec_tokens} "
        f"kv_block={kv_block} mega={mega} lora={n_lora}")

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    _set_stage("engine-init")
    t0 = time.time()
    engine = InferenceEngine(
        model, n_slots=n_slots, max_len=max_len, tokenizer=ByteTokenizer(),
        window_k=int(os.environ.get("BENCH_WINDOW", "8")),
        pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
        quant=quant,
        kv_quant=kv_quant,
        spec_tokens=spec_tokens,
        kv_block=kv_block,
        mega_windows=mega,
        prefill_depth=int(os.environ.get("BENCH_PREFILL_DEPTH", "1")),
        lora_slots=n_lora,
        lora_rank=lora_rank,
    )
    engine.start_sync()
    log(f"engine up in {time.time() - t0:.1f}s")
    adapters = [""]
    if n_lora:
        import jax as _jax

        from gofr_tpu.models.transformer import lora_dims

        _set_stage("lora-load")
        for ai in range(n_lora):
            leaves = {}
            for ti, t in enumerate(("wq", "wk", "wv", "wo")):
                d_in, d_out = lora_dims(engine.cfg, t)
                k1, k2 = _jax.random.split(
                    _jax.random.fold_in(_jax.random.PRNGKey(1000 + ai), ti),
                    2,
                )
                leaves[t] = (
                    0.02 * _jax.random.normal(
                        k1, (engine.cfg.n_layers, d_in, lora_rank)
                    ),
                    0.02 * _jax.random.normal(
                        k2, (engine.cfg.n_layers, lora_rank, d_out)
                    ),
                )
            engine.load_lora(f"bench-{ai}", leaves)
            adapters.append(f"bench-{ai}")
        log(f"loaded {n_lora} rank-{lora_rank} adapters; requests cycle "
            f"over base + adapters")

    prompt = "The quick brown fox jumps over the lazy dog. " * 3  # ~135 bytes

    # Device profile BEFORE the scheduler starts (doubles as compile
    # warmup): per-window device time vs fetch RTT, achieved HBM GB/s vs
    # peak — so the throughput number below is attributable (VERDICT r1
    # weak #4: "nobody knows where it goes").
    _set_stage("profile")
    t0 = time.time()
    engine.stop_sync()
    prof = engine.profile_decode(n_windows=8)
    engine.start_sync()
    step_ms = prof["step_s"] * 1e3
    pbytes = engine.param_bytes()
    peak_gbps = float(os.environ.get("BENCH_HBM_PEAK_GBPS", "819"))
    gbps = pbytes / prof["step_s"] / 1e9
    device_bound_tps = n_slots / prof["step_s"]
    log(f"profile: decode window({engine.window_k} steps)="
        f"{prof['window_s'] * 1e3:.1f}ms → step={step_ms:.2f}ms; "
        f"host<->device rtt={prof['rtt_s'] * 1e3:.1f}ms; "
        f"prefill chunk({engine.prefill_batch}x{engine.prefill_chunk})="
        f"{prof['prefill_s'] * 1e3:.1f}ms")
    log(f"profile: weight stream {pbytes / 1e9:.2f} GB/step → "
        f"{gbps:.0f} GB/s = {100 * gbps / peak_gbps:.0f}% of "
        f"{peak_gbps:.0f} GB/s peak (weight-stream bound: "
        f"{peak_gbps * 1e9 / pbytes * n_slots:.0f} tok/s; device-bound: "
        f"{device_bound_tps:.0f} tok/s)")

    # Decode-attention path A/B (kernel grid vs fused dense) at the real
    # serving shapes and kv dtype — answers GOFR_TPU_FLASH_DECODE's
    # question from one run. Kernel probe only where it compiles natively
    # (interpret mode off-TPU is meaninglessly slow). Helper scope so the
    # probe tensors (GB-scale at 8B/8k shapes) free before the measured run.
    if on_tpu:
        _decode_attn_ab(engine, n_slots, kv_quant)
        _prefill_attn_ab(engine, n_slots, kv_quant)
    log(f"profile in {time.time() - t0:.1f}s")

    # Warmup: compile the real prefill bucket + steady-state decode path.
    _set_stage("warmup")
    t0 = time.time()
    engine.generate_sync(prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False)
    log(f"warmup (compile) in {time.time() - t0:.1f}s")
    # Warm-up fence: every serving program the measured run will touch
    # is compiled; a compile past this point serializes the measurement
    # behind XLA and is a fixed-shape bug — exit 6 (no JSON) below.
    engine.mark_steady_state()

    # Measured run: n_requests concurrent, engine batches them over n_slots.
    # BENCH_ARRIVAL_MS staggers submissions (0 = one synchronized burst,
    # which quantizes retirements into waves and understates continuous
    # batching); BENCH_TOKEN_SPREAD varies budgets ±fraction so slots
    # retire and refill independently, the steady state real serving
    # lives in.
    import random

    # The TPU default workload is STEADY-STATE (staggered arrivals, varied
    # budgets): a synchronized burst quantizes retirements into waves and
    # the end-to-end number divides by ramp/drain phases, understating
    # continuous batching and confounding round-over-round deltas
    # (VERDICT r3 #10). BENCH_ARRIVAL_MS=0 BENCH_TOKEN_SPREAD=0 restores
    # the burst workload for A/Bs against pre-r4 campaign rows.
    arrival_ms = float(
        os.environ.get("BENCH_ARRIVAL_MS", "25" if on_tpu else "0")
    )
    spread = float(
        os.environ.get("BENCH_TOKEN_SPREAD", "0.5" if on_tpu else "0")
    )
    rng = random.Random(0)
    _set_stage("measure")
    t0 = time.time()
    reqs = []
    for i in range(n_requests):
        if arrival_ms > 0 and i:
            time.sleep(arrival_ms / 1e3)
        nt = new_tokens
        if spread > 0:
            nt = max(8, int(new_tokens * (1 - spread + 2 * spread * rng.random())))
        reqs.append(engine.submit_generate(
            prompt, max_new_tokens=nt, temperature=0.0, stop_on_eos=False,
            adapter=adapters[i % len(adapters)],
        ))
    results = [r.future.result(timeout=1800) for r in reqs]
    # NB: must not be named `wall` — that would rebind the watchdog
    # closure's deadline and kill the run at the unloaded-ttft stage.
    measure_wall = time.time() - t0

    total_tokens = sum(len(r.token_ids) for r in results)
    tps = total_tokens / measure_wall
    ttfts = sorted(r.ttft_s * 1e3 for r in results)
    p50 = statistics.median(ttfts)
    p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]
    # Tail-latency fields for the JSON line: per-request TTFT and
    # inter-token latency p50/p95/p99 — BENCH_* trajectories must
    # capture the tail, not just throughput.
    latency = _latency_fields(results)

    log(f"generated {total_tokens} tokens in {measure_wall:.2f}s "
        f"→ {tps:.1f} tok/s/chip end-to-end")
    log(f"ITL p50={latency['itl_p50']}ms p95={latency['itl_p95']}ms "
        f"p99={latency['itl_p99']}ms (per-request mean gap between "
        f"generated tokens)")
    workload = "burst"
    steady_tps = None
    if arrival_ms > 0 or spread > 0:
        # Steady-state estimate for staggered runs: the overall number
        # above divides by the ramp-up and drain phases too, understating
        # continuous batching. Use the middle half of the completion
        # timeline (25th→75th percentile completion) — and REPORT it as
        # the headline value: it is the number a loaded replica actually
        # sustains (VERDICT r3 #10). The end-to-end rate stays in the
        # JSON as e2e_tps for cross-checking.
        comps = sorted(
            (q.enqueued_at + r.duration_s, len(r.token_ids))
            for q, r in zip(reqs, results)
        )
        lo, hi = comps[len(comps) // 4][0], comps[3 * len(comps) // 4][0]
        mid_tokens = sum(n for t, n in comps if lo < t <= hi)
        if hi > lo and mid_tokens:
            workload = "steady"
            steady_tps = mid_tokens / (hi - lo)
            log(f"steady-state (middle half of completions): "
                f"{steady_tps:.1f} tok/s/chip — reported as the headline "
                f"value; NOT comparable to burst rows")
        else:
            # Label must not claim steady when the value is end-to-end —
            # harvesters compare JSON lines by workload.
            workload = "steady-degenerate-e2e"
            log("steady-state window degenerate (too few/fast completions)"
                " — falling back to the end-to-end rate")
    log(f"TTFT p50={p50:.1f}ms p99={p99:.1f}ms (includes queueing behind "
        f"{n_requests} concurrent requests on {n_slots} slots)")

    # Unloaded TTFT: sequential single requests against an idle engine —
    # the honest latency number (north star: p50 < 50ms, BASELINE.json).
    _set_stage("unloaded-ttft")
    unloaded = []
    for _ in range(5):
        r = engine.generate_sync(
            prompt, max_new_tokens=2, temperature=0.0, stop_on_eos=False
        )
        unloaded.append(r.ttft_s * 1e3)
    log(f"unloaded TTFT p50={statistics.median(unloaded):.1f}ms "
        f"(min={min(unloaded):.1f} max={max(unloaded):.1f}, "
        f"short prompt, empty queue)")

    device_fields = _device_resource_fields(engine)
    loop_fields = _loop_fields(engine)
    _recompile_guard(engine)
    engine.stop_sync()
    _set_stage("done")

    # platform/degraded: a CPU fallback number must never impersonate the
    # TPU tok/s/chip artifact (VERDICT r2 weak #3).
    headline = steady_tps if steady_tps is not None else tps
    row = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(headline, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(headline / 1000.0, 4),
        "platform": platform,
        "degraded": platform != "tpu",
        "model": model,
        "workload": workload,
        "e2e_tps": round(tps, 2),
        **latency,
        **device_fields,
        **loop_fields,
        **({"lora": n_lora} if n_lora else {}),
    }
    row.update(_trajectory_fields(row))
    print(json.dumps(row), flush=True)

    # Skip interpreter teardown: the TPU runtime client keeps background
    # threads that can panic when Python finalizes while they unwind,
    # turning a successful bench into exit 134. The JSON is out; exit clean.
    os._exit(0)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        sys.exit(run_with_retry())
