"""Headline benchmark — flagship LLM serving throughput on TPU.

Boots the serving engine (continuous batching, fused decode+sample, donated
KV cache) with the largest Llama-family config that fits the available chip,
runs concurrent generation, and prints ONE JSON line:

    {"metric": "decode_tokens_per_sec_per_chip", "value": N,
     "unit": "tok/s/chip", "vs_baseline": N/1000}

``vs_baseline``: the reference (GoFr) publishes no perf numbers
(BASELINE.md), so the denominator is a fixed 1000 tok/s/chip nominal
target for a ~1B bf16 model on one v5e — chosen once so the ratio is
comparable across rounds. Details (TTFT p50/p99, per-request rates) go to
stderr.

Env knobs: BENCH_MODEL (default llama-1b on TPU, llama-tiny on CPU),
BENCH_REQUESTS (default 64), BENCH_NEW_TOKENS (default 128),
BENCH_SLOTS (default 32), BENCH_MAX_LEN (default 1024),
BENCH_WINDOW (default 8), BENCH_DEPTH (default 2),
BENCH_QUANT (default int8 on TPU — weight-only int8, the production
serving configuration; set BENCH_QUANT=none for bf16 weights).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    model = os.environ.get("BENCH_MODEL", "llama-1b" if on_tpu else "llama-tiny")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
    n_slots = int(os.environ.get("BENCH_SLOTS", "32"))
    max_len = int(os.environ.get("BENCH_MAX_LEN", "1024"))
    quant = os.environ.get("BENCH_QUANT", "int8" if on_tpu else "")
    if quant.lower() in ("none", "0"):
        quant = ""

    log(f"bench: platform={platform} model={model} requests={n_requests} "
        f"new_tokens={new_tokens} slots={n_slots} quant={quant or 'bf16'}")

    from gofr_tpu.serving.engine import InferenceEngine
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    t0 = time.time()
    engine = InferenceEngine(
        model, n_slots=n_slots, max_len=max_len, tokenizer=ByteTokenizer(),
        window_k=int(os.environ.get("BENCH_WINDOW", "8")),
        pipeline_depth=int(os.environ.get("BENCH_DEPTH", "2")),
        quant=quant,
    )
    engine.start_sync()
    log(f"engine up in {time.time() - t0:.1f}s")

    prompt = "The quick brown fox jumps over the lazy dog. " * 3  # ~135 bytes

    # Warmup: compile prefill + decode once.
    t0 = time.time()
    engine.generate_sync(prompt, max_new_tokens=4, temperature=0.0, stop_on_eos=False)
    log(f"warmup (compile) in {time.time() - t0:.1f}s")

    # Measured run: n_requests concurrent, engine batches them over n_slots.
    t0 = time.time()
    reqs = [
        engine.submit_generate(
            prompt, max_new_tokens=new_tokens, temperature=0.0, stop_on_eos=False
        )
        for _ in range(n_requests)
    ]
    results = [r.future.result(timeout=1800) for r in reqs]
    wall = time.time() - t0

    total_tokens = sum(len(r.token_ids) for r in results)
    tps = total_tokens / wall
    ttfts = sorted(r.ttft_s * 1e3 for r in results)
    p50 = statistics.median(ttfts)
    p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]

    log(f"generated {total_tokens} tokens in {wall:.2f}s → {tps:.1f} tok/s/chip")
    log(f"TTFT p50={p50:.1f}ms p99={p99:.1f}ms (includes queueing behind "
        f"{n_requests} concurrent requests on {n_slots} slots)")

    engine.stop_sync()

    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tps, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tps / 1000.0, 4),
    }), flush=True)

    # Skip interpreter teardown: the TPU runtime client keeps background
    # threads that can panic when Python finalizes while they unwind,
    # turning a successful bench into exit 134. The JSON is out; exit clean.
    os._exit(0)


if __name__ == "__main__":
    main()
