// Byte-level BPE tokenizer with a C ABI, for the serving runtime's ingress
// path (tokenization runs off the GIL; the Python binding is
// gofr_tpu/serving/native_tokenizer.py, which also carries the pure-Python
// oracle the tests compare against).
//
// The reference framework is pure Go with no native components (SURVEY §2);
// this is net-new runtime code for the TPU serving graft: prompt encoding
// is the only CPU-bound ingress work in the engine hot path.
//
// File formats (written by the Python side, see write_bpe_files):
//   vocab:  one token per line, hex-encoded bytes; line number = token id.
//   merges: "hexA hexB" per line; line number = merge rank (lower = earlier).
//
// Build: g++ -O2 -shared -fPIC -o libbpe.so bpe_tokenizer.cpp

#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

std::string hex_decode(const std::string& hex) {
  std::string out;
  out.reserve(hex.size() / 2);
  for (size_t i = 0; i + 1 < hex.size(); i += 2) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      return -1;
    };
    int hi = nib(hex[i]), lo = nib(hex[i + 1]);
    if (hi < 0 || lo < 0) break;
    out.push_back(static_cast<char>((hi << 4) | lo));
  }
  return out;
}

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    return std::hash<std::string>()(p.first) * 1000003u ^
           std::hash<std::string>()(p.second);
  }
};

struct Tokenizer {
  std::unordered_map<std::string, int32_t> vocab;
  std::vector<std::string> id_to_token;
  std::unordered_map<std::pair<std::string, std::string>, int32_t, PairHash>
      merge_rank;
};

}  // namespace

extern "C" {

void* bpe_create(const char* vocab_path, const char* merges_path) {
  auto* t = new Tokenizer();
  std::ifstream vf(vocab_path);
  if (!vf) {
    delete t;
    return nullptr;
  }
  std::string line;
  int32_t id = 0;
  while (std::getline(vf, line)) {
    std::string tok = hex_decode(line);
    t->vocab.emplace(tok, id);
    t->id_to_token.push_back(tok);
    ++id;
  }
  std::ifstream mf(merges_path);
  if (mf) {
    int32_t rank = 0;
    while (std::getline(mf, line)) {
      auto sp = line.find(' ');
      if (sp == std::string::npos) continue;
      t->merge_rank.emplace(
          std::make_pair(hex_decode(line.substr(0, sp)),
                         hex_decode(line.substr(sp + 1))),
          rank++);
    }
  }
  return t;
}

void bpe_free(void* h) { delete static_cast<Tokenizer*>(h); }

int32_t bpe_vocab_size(void* h) {
  return static_cast<int32_t>(static_cast<Tokenizer*>(h)->id_to_token.size());
}

// Greedy lowest-rank-first BPE over raw bytes. Returns the number of ids
// written, or -needed if out_cap is too small, or -1 on error.
int32_t bpe_encode(void* h, const char* text, int32_t text_len, int32_t* out,
                   int32_t out_cap) {
  auto* t = static_cast<Tokenizer*>(h);
  if (t == nullptr || text == nullptr) return -1;

  std::vector<std::string> symbols;
  symbols.reserve(text_len);
  for (int32_t i = 0; i < text_len; ++i) symbols.emplace_back(1, text[i]);

  while (symbols.size() > 1) {
    int32_t best_rank = INT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < symbols.size(); ++i) {
      auto it = t->merge_rank.find({symbols[i], symbols[i + 1]});
      if (it != t->merge_rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == INT32_MAX) break;
    symbols[best_i] += symbols[best_i + 1];
    symbols.erase(symbols.begin() + best_i + 1);
  }

  // Map to ids; symbols missing from the vocab fall back to per-byte ids
  // (byte-level BPE vocabs always contain every single byte).
  std::vector<int32_t> ids;
  ids.reserve(symbols.size());
  for (const auto& s : symbols) {
    auto it = t->vocab.find(s);
    if (it != t->vocab.end()) {
      ids.push_back(it->second);
    } else {
      for (char c : s) {
        auto bt = t->vocab.find(std::string(1, c));
        ids.push_back(bt != t->vocab.end() ? bt->second : 0);
      }
    }
  }
  if (static_cast<int32_t>(ids.size()) > out_cap)
    return -static_cast<int32_t>(ids.size());
  std::memcpy(out, ids.data(), ids.size() * sizeof(int32_t));
  return static_cast<int32_t>(ids.size());
}

// Concatenate token byte-strings. Returns bytes written, -needed if the
// buffer is too small, or -1 on error.
int32_t bpe_decode(void* h, const int32_t* ids, int32_t n, char* out,
                   int32_t out_cap) {
  auto* t = static_cast<Tokenizer*>(h);
  if (t == nullptr || ids == nullptr) return -1;
  std::string buf;
  for (int32_t i = 0; i < n; ++i) {
    if (ids[i] >= 0 && ids[i] < static_cast<int32_t>(t->id_to_token.size()))
      buf += t->id_to_token[ids[i]];
  }
  if (static_cast<int32_t>(buf.size()) > out_cap)
    return -static_cast<int32_t>(buf.size());
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int32_t>(buf.size());
}

}  // extern "C"
