"""Train → checkpoint → serve, end to end (net-new: the reference is a
microservice framework with no model code; this is the TPU-native loop a
GoFr user migrating to gofr_tpu gets on top of the familiar app surface).

``python main.py train`` runs a few sharded training steps on synthetic
data and writes an orbax checkpoint; ``python main.py serve`` boots the
HTTP app whose engine restores that checkpoint (``TPU_CHECKPOINT``) and
generates from it. The CLI app and HTTP app are the same framework
surfaces every other example uses.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

CKPT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ckpt")


def build_cmd():
    from gofr_tpu import new_cmd

    app = new_cmd(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.sub_command("^train")
    def train(ctx):
        import jax
        import jax.numpy as jnp

        from gofr_tpu.models.registry import get_model
        from gofr_tpu.parallel import make_mesh, make_train_step
        from gofr_tpu.serving.checkpoint import save_checkpoint

        steps = int(ctx.param("steps") or "4")
        cfg = get_model("llama-tiny").config
        # One-device mesh here so the example runs anywhere; swap the
        # axes dict for {"dp": 2, "tp": 2, ...} on real hardware.
        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        init_state, train_step, _ = make_train_step(cfg, mesh, sp=False)
        params, opt_state = init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size
        )
        loss = None
        for _ in range(steps):
            loss, params, opt_state = train_step(params, opt_state, tokens)
        # Serving restores bf16/f32 params; drop the optimizer state.
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x,
            params,
        )
        save_checkpoint(CKPT, params)
        return {"steps": steps, "final_loss": float(loss), "checkpoint": CKPT}

    return app


def build_app():
    from gofr_tpu import App

    os.environ.setdefault("TPU_ENABLED", "true")
    os.environ.setdefault("TPU_MODEL", "llama-tiny")
    os.environ.setdefault("TPU_CHECKPOINT", CKPT)
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.post("/generate")
    async def generate(ctx):
        body = ctx.request.json()
        out = await ctx.infer(
            body.get("prompt", "hello"),
            max_new_tokens=int(body.get("max_new_tokens", 16)),
            temperature=float(body.get("temperature", 0.0)),
        )
        return out

    return app


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        sys.argv.pop(1)
        build_app().run()
    else:
        raise SystemExit(build_cmd().run())
