"""Redis-backed HTTP server (reference ``examples/http-server-using-redis``).

GET /redis/{key} reads a key, POST /redis stores {"key": ..., "value": ...}.
Configure REDIS_HOST/REDIS_PORT; run a server with
``python -m gofr_tpu.datasource.redis.miniredis`` or any real Redis.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.errors import ErrorEntityNotFound


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.get("/redis/{key}")
    def get_key(ctx):
        value = ctx.redis.get(ctx.path_param("key"))
        if value is None:
            raise ErrorEntityNotFound("key", ctx.path_param("key"))
        return {"key": ctx.path_param("key"), "value": value}

    @app.post("/redis")
    def set_key(ctx):
        body = ctx.request.json()
        ctx.redis.set(body["key"], body["value"])
        return {"stored": body["key"]}

    return app


if __name__ == "__main__":
    main().run()
