"""Train a LoRA adapter → export it HF-PEFT style → serve it multi-LoRA.

Net-new capability on the familiar app surfaces (the reference is a
microservice framework with no model code): ``python main.py train``
fine-tunes rank-8 adapter factors on a FROZEN llama-tiny base with the
framework's own LoRA train step and writes an HF-PEFT-format adapter
dir (``adapter_config.json`` + safetensors — loadable by this framework
or any PEFT consumer); ``python main.py serve`` boots the OpenAI app
with the adapter preloaded (``TPU_LORA_ADAPTERS``), where it serves as
model id "tuned" next to the base model — one engine, one batch, both
models.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

ADAPTER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "adapter")
CORPUS = b"gofr serves tpus with adapters. " * 8
RANK = 8
TARGETS = ("wq", "wk", "wv", "wo")
_PEFT_MODULE = {"wq": "q_proj", "wk": "k_proj", "wv": "v_proj", "wo": "o_proj"}


def build_cmd():
    from gofr_tpu import new_cmd

    app = new_cmd(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.sub_command("^train")
    def train(ctx):
        import jax
        import numpy as np
        from safetensors.numpy import save_file

        from gofr_tpu.models.registry import get_model
        from gofr_tpu.models.transformer import init_transformer
        from gofr_tpu.parallel.sharding import make_lora_train_step

        steps = int(ctx.param("steps") or "60")
        cfg = get_model("llama-tiny").config
        # The SAME base the serving engine random-inits (seed 0), so the
        # adapter trained here plugs straight into `serve`.
        base = init_transformer(jax.random.PRNGKey(0), cfg)
        init_state, step = make_lora_train_step(
            cfg, base, rank=RANK, targets=TARGETS, learning_rate=3e-3
        )
        lora, opt = init_state(jax.random.PRNGKey(1))
        toks = np.frombuffer(CORPUS, dtype=np.uint8).astype(np.int32)
        toks = toks[None, :128]
        loss = None
        for _ in range(steps):
            loss, lora, opt = step(lora, opt, toks)

        # Export HF-PEFT layout: per-layer lora_A [r, d_in] / lora_B
        # [d_out, r]; our b already carries the scale, so alpha=r makes
        # PEFT's alpha/r factor exactly 1.
        os.makedirs(ADAPTER, exist_ok=True)
        tensors = {}
        for t in TARGETS:
            a, b = np.asarray(lora[t][0]), np.asarray(lora[t][1])
            for i in range(cfg.n_layers):
                mod = _PEFT_MODULE[t]
                pre = f"base_model.model.model.layers.{i}.self_attn.{mod}"
                tensors[f"{pre}.lora_A.weight"] = a[i].T.astype(np.float32)
                tensors[f"{pre}.lora_B.weight"] = b[i].T.astype(np.float32)
        save_file(tensors, os.path.join(ADAPTER, "adapter_model.safetensors"))
        with open(os.path.join(ADAPTER, "adapter_config.json"), "w") as f:
            json.dump({
                "r": RANK,
                "lora_alpha": RANK,
                "target_modules": [_PEFT_MODULE[t] for t in TARGETS],
            }, f)
        return {
            "steps": steps,
            "final_loss": float(loss),
            "adapter": ADAPTER,
        }

    return app


def build_app():
    from gofr_tpu import App
    from gofr_tpu.serving.openai_compat import add_openai_routes

    os.environ.setdefault("TPU_ENABLED", "true")
    os.environ.setdefault("TPU_MODEL", "llama-tiny")
    os.environ.setdefault("TPU_LORA_SLOTS", "2")
    os.environ.setdefault("TPU_LORA_RANK", str(RANK))
    os.environ.setdefault("TPU_LORA_ADAPTERS", f"tuned={ADAPTER}")
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    add_openai_routes(app)
    return app


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        sys.argv.pop(1)
        build_app().run()
    else:
        raise SystemExit(build_cmd().run())
