"""gRPC inference server example (BASELINE.json config 5 shape: LLM chat
over gRPC unary + stream; reference analog ``examples/grpc-server``).

Serves gofr.tpu.Inference on :9000 plus the HTTP health surface on :8000.
Model selected by TPU_MODEL in configs/.env (llama-tiny by default so the
example runs anywhere; set llama-1b/llama-3-8b on real hardware).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.grpc import (
    TypedInferenceServicer,
    add_inference_service,
    add_typed_inference_service,
)
from gofr_tpu.grpc.inference import InferenceServicer


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    engine = app.container.tpu
    if engine is None:
        raise SystemExit("set TPU_MODEL in configs/.env")
    # Typed protobuf contract (gofr.tpu.v1.Inference) + JSON exploration
    # surface (gofr.tpu.Inference) on the same :9000 server.
    app.register_service(
        add_typed_inference_service, TypedInferenceServicer(engine)
    )
    app.register_service(add_inference_service, InferenceServicer(engine))

    @app.get("/models")
    def models(ctx):
        return ctx.tpu.health_check()["details"]

    return app


if __name__ == "__main__":
    main().run()
