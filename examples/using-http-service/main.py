"""Inter-service HTTP client (reference ``examples/using-http-service``).

Registers a downstream dependency at boot (``app.add_http_service``) and
calls it from a handler via ``ctx.http_service`` — spans, logs, and the
``app_http_service_response`` histogram come from the client stack; the
dependency joins ``/.well-known/health``. DOWNSTREAM_ADDR points at the
dependency (in the reference the example points at itself on localhost).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    downstream = app.container.config.get_or_default(
        "DOWNSTREAM_ADDR", f"http://localhost:{app.http_port}"
    )
    app.add_http_service("catalog", downstream)

    @app.get("/item")
    def item(ctx):
        # Proxy through the service client to the downstream /raw-item.
        resp = ctx.http_service("catalog").get("/raw-item")
        return {"downstream_status": resp.status_code, "body": resp.json()}

    @app.get("/raw-item")
    def raw_item(ctx):
        return {"sku": "tpu-pod", "stock": 256}

    return app


if __name__ == "__main__":
    main().run()
