"""CRUD generator example (reference ``examples/using-add-rest-handlers``):
a dataclass entity gets five SQL-backed REST routes, created via migration."""

import os
import sys
from dataclasses import dataclass

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App, Migrate


@dataclass
class User:
    id: int = 0
    name: str = ""
    age: int = 0


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    app.migrate({
        1: Migrate(up=lambda ds: ds.sql.exec(
            "CREATE TABLE IF NOT EXISTS user "
            "(id INTEGER PRIMARY KEY, name TEXT, age INTEGER)"
        )),
    })
    app.add_rest_handlers(User)
    return app


if __name__ == "__main__":
    main().run()
