"""Custom app metrics (reference ``examples/using-custom-metrics``).

Registers counter/updown/histogram/gauge instruments at boot and records
them from handlers; scrape them on the metrics port
(``curl :2121/metrics``).
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    m = app.container.metrics
    m.new_counter("orders_created", "orders created via POST /order")
    m.new_updown_counter("orders_open", "orders currently open")
    m.new_histogram(
        "order_value_dollars", "order value distribution",
        buckets=[1, 5, 10, 50, 100, 500],
    )
    m.new_gauge("last_order_unix", "time of most recent order")

    @app.post("/order")
    def create_order(ctx):
        body = ctx.request.json()
        ctx.metrics.increment_counter("orders_created", "product", body["product"])
        ctx.metrics.delta_updown_counter("orders_open", 1)
        ctx.metrics.record_histogram("order_value_dollars", float(body["value"]))
        ctx.metrics.set_gauge("last_order_unix", time.time())
        return {"ok": True}

    @app.delete("/order/{id}")
    def close_order(ctx):
        ctx.metrics.delta_updown_counter("orders_open", -1)
        return None

    return app


if __name__ == "__main__":
    main().run()
