"""Offline batch inference over pub/sub (BASELINE.json config 4 shape:
subscriber → batch infer → publisher; reference analog
``examples/using-subscriber`` + ``using-publisher``).

Consumes JSON {"id": ..., "prompt": ...} messages from topic ``infer-requests``,
generates, and publishes {"id", "text"} to ``infer-responses``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.subscribe("infer-requests")
    async def handle(ctx):
        payload = ctx.request.json()
        result = await ctx.infer(
            payload.get("prompt", ""), max_new_tokens=16, stop_on_eos=False
        )
        ctx.publish(
            "infer-responses",
            json.dumps({"id": payload.get("id"), "text": result["text"]}).encode(),
        )

    @app.post("/submit")
    def submit(ctx):
        body = ctx.request.json()
        ctx.publish("infer-requests", json.dumps(body).encode())
        return {"queued": True}

    @app.get("/results")
    def results(ctx):
        out = []
        while True:
            msg = ctx.pubsub.subscribe("infer-responses", timeout=0.05)
            if msg is None:
                break
            msg.commit()
            out.append(json.loads(msg.value))
        return out

    return app


if __name__ == "__main__":
    main().run()
