"""Versioned data migrations (reference ``examples/using-migrations``).

``app.migrate({version: Migrate(up=...)})`` runs pending migrations in
order inside transactions and records them in the ``gofr_migrations``
table, so restarts resume where they left off (reference
``migration/migration.go:12-79``).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.migration import Migrate


def create_employee_table(ds) -> None:
    ds.sql.exec(
        "CREATE TABLE IF NOT EXISTS employee "
        "(id INTEGER PRIMARY KEY, name TEXT, dept TEXT)"
    )


def seed_employees(ds) -> None:
    ds.sql.exec("INSERT INTO employee (name, dept) VALUES (?, ?)", "ada", "infra")
    ds.sql.exec("INSERT INTO employee (name, dept) VALUES (?, ?)", "bo", "ml")


ALL = {
    20240226153000: Migrate(up=create_employee_table),
    20240226153100: Migrate(up=seed_employees),
}


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    app.migrate(ALL)

    @app.get("/employees")
    def employees(ctx):
        return ctx.sql.query("SELECT id, name, dept FROM employee ORDER BY id")

    return app


if __name__ == "__main__":
    main().run()
