"""HTTP server example (reference ``examples/http-server/main.go``).

Routes: /hello (query param), /params/{id} (path param), /bind (JSON bind),
/error (typed error → status), /redis + /sql when those datasources are
configured. Run with `python main.py`; serves :8000 (override HTTP_PORT).
"""

import os
import sys
from dataclasses import dataclass

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.errors import ErrorEntityNotFound


@dataclass
class Person:
    name: str = ""
    age: int = 0


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.get("/hello")
    def hello(ctx):
        name = ctx.param("name") or "World"
        return f"Hello {name}!"

    @app.get("/params/{id}")
    def params(ctx):
        return {"id": ctx.path_param("id")}

    @app.post("/bind")
    def bind(ctx):
        person = ctx.bind(Person)
        return {"name": person.name, "age": person.age}

    @app.get("/error")
    def error(ctx):
        raise ErrorEntityNotFound("id", ctx.param("id") or "unknown")

    @app.get("/trace")
    def trace(ctx):
        with ctx.trace("example-work"):
            total = sum(range(1000))
        return {"sum": total}

    return app


if __name__ == "__main__":
    main().run()
