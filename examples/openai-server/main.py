"""OpenAI-compatible LLM server: point any OpenAI SDK's base_url here.

POST /v1/completions        {"prompt": "...", "max_tokens": 32, "stream": true}
POST /v1/chat/completions   {"messages": [{"role": "user", "content": "hi"}]}
GET  /v1/models
POST /v1/files              multipart JSONL upload (purpose=batch)
POST /v1/batches            offline batch inference over the uploads
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.serving.openai_batch import add_openai_batch_routes
from gofr_tpu.serving.openai_compat import add_openai_routes


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))
    add_openai_routes(app)
    add_openai_batch_routes(app)
    return app


if __name__ == "__main__":
    main().run()
