"""Multipart file upload binding (reference ``examples/using-file-bind``).

POST /upload with multipart/form-data: a ``file`` part binds to
:class:`UploadedFile` and a ``name`` field binds by name — the dataclass
walk the reference does in ``http/multipartFileBind.go``.
"""

import os
import sys
from dataclasses import dataclass
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App
from gofr_tpu.http.request import UploadedFile


@dataclass
class UploadForm:
    name: str = ""
    file: Optional[UploadedFile] = None


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.post("/upload")
    def upload(ctx):
        form = ctx.bind(UploadForm)
        return {
            "name": form.name,
            "filename": form.file.filename if form.file else None,
            "size": len(form.file.data) if form.file else 0,
        }

    return app


if __name__ == "__main__":
    main().run()
