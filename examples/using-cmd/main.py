"""CLI example (reference ``examples/using-cmd/main.go``): subcommands with
flags binding into params."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import new_cmd


def main():
    app = new_cmd(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.sub_command("^hello")
    def hello(ctx):
        name = ctx.param("name") or "World"
        return f"Hello {name}!"

    @app.sub_command("^params")
    def params(ctx):
        return {"flags": {k: ctx.param(k) for k in ("a", "b", "verbose")}}

    return app


if __name__ == "__main__":
    raise SystemExit(main().run())
