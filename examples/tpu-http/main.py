"""HTTP inference example (BASELINE.json config 2 shape: image classify over
HTTP POST, plus text generate).

POST /generate {"prompt": "...", "max_new_tokens": 32}
POST /classify {"image": [[...]]} (HxWx3 nested lists)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.post("/generate")
    async def generate(ctx):
        body = ctx.request.json()
        return await ctx.infer(
            body.get("prompt", ""),
            max_new_tokens=int(body.get("max_new_tokens", 32)),
            temperature=float(body.get("temperature", 0.0)),
            stop_on_eos=False,
        )

    return app


if __name__ == "__main__":
    main().run()
