"""Publishing to pub/sub from HTTP handlers (reference
``examples/using-publisher``): POST /publish-order forwards the JSON body
to the ``order-logs`` topic; pair with ``using-subscriber`` for the
consuming side.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from gofr_tpu import App


def main() -> App:
    app = App(config_dir=os.path.join(os.path.dirname(__file__), "configs"))

    @app.post("/publish-order")
    def publish_order(ctx):
        body = ctx.request.json()
        ctx.publish("order-logs", json.dumps(body).encode())
        return {"published": True}

    @app.get("/peek")
    def peek(ctx):
        # Demo-only: drain one message so the example is self-contained.
        msg = ctx.pubsub.subscribe("order-logs", timeout=0.05)
        if msg is None:
            return {"empty": True}
        msg.commit()
        return {"message": json.loads(msg.value)}

    return app


if __name__ == "__main__":
    main().run()
