"""Mongo datasource seam — interface only, driver injected by the user.

Reference: ``pkg/gofr/datasource/mongo.go:8-53`` defines an 11-method CRUD
interface and ships **no driver**; apps call ``App.UseMongo`` with their own
client (``gofr.go:376-378``, doc
``docs/advanced-guide/injecting-databases-drivers``). Same here:
:class:`Mongo` is a :class:`typing.Protocol` the injected client must
satisfy; ``app.use_mongo(client)`` stores it on the container and
``ctx.mongo`` hands it to handlers. A client exposing ``health_check()``
joins the aggregate ``/.well-known/health`` report.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable


@runtime_checkable
class Mongo(Protocol):
    """CRUD surface mirroring the reference interface (``mongo.go:8-53``)."""

    def find(self, collection: str, filter: dict, results: Any) -> None: ...
    def find_one(self, collection: str, filter: dict, result: Any) -> None: ...
    def insert_one(self, collection: str, document: dict) -> Any: ...
    def insert_many(self, collection: str, documents: list) -> Any: ...
    def delete_one(self, collection: str, filter: dict) -> int: ...
    def delete_many(self, collection: str, filter: dict) -> int: ...
    def update_by_id(self, collection: str, id: Any, update: dict) -> int: ...
    def update_one(self, collection: str, filter: dict, update: dict) -> None: ...
    def update_many(self, collection: str, filter: dict, update: dict) -> int: ...
    def count_documents(self, collection: str, filter: dict) -> int: ...
    def drop(self, collection: str) -> None: ...


class InMemoryMongo:
    """Dict-backed :class:`Mongo` implementation — the test double apps can
    inject (the role miniredis plays for Redis, SURVEY §4)."""

    def __init__(self) -> None:
        self._collections: dict[str, list[dict]] = {}
        self._next_id = 0

    def _coll(self, name: str) -> list[dict]:
        return self._collections.setdefault(name, [])

    @staticmethod
    def _matches(doc: dict, filter: dict) -> bool:
        return all(doc.get(k) == v for k, v in (filter or {}).items())

    @staticmethod
    def _apply_update(doc: dict, update: dict) -> None:
        """Mongo update-operator semantics ($set/$inc/$unset). Operator-less
        documents are rejected like real MongoDB rejects them for update_*,
        so code that passes against this double also works on a driver."""
        if not update or not all(k.startswith("$") for k in update):
            raise ValueError(
                "update document must use operators, e.g. {'$set': {...}}"
            )
        for op, fields in update.items():
            if op == "$set":
                doc.update(fields)
            elif op == "$inc":
                for k, v in fields.items():
                    doc[k] = doc.get(k, 0) + v
            elif op == "$unset":
                for k in fields:
                    doc.pop(k, None)
            else:
                raise ValueError(f"unsupported update operator {op!r}")

    def find(self, collection: str, filter: dict, results: list) -> None:
        results.extend(
            dict(d) for d in self._coll(collection) if self._matches(d, filter)
        )

    def find_one(self, collection: str, filter: dict, result: dict) -> None:
        for d in self._coll(collection):
            if self._matches(d, filter):
                result.update(d)
                return

    def insert_one(self, collection: str, document: dict) -> Any:
        doc = dict(document)
        if "_id" not in doc:
            self._next_id += 1
            doc["_id"] = self._next_id
        self._coll(collection).append(doc)
        return doc["_id"]

    def insert_many(self, collection: str, documents: list) -> list:
        return [self.insert_one(collection, d) for d in documents]

    def delete_one(self, collection: str, filter: dict) -> int:
        coll = self._coll(collection)
        for i, d in enumerate(coll):
            if self._matches(d, filter):
                del coll[i]
                return 1
        return 0

    def delete_many(self, collection: str, filter: dict) -> int:
        coll = self._coll(collection)
        keep = [d for d in coll if not self._matches(d, filter)]
        removed = len(coll) - len(keep)
        self._collections[collection] = keep
        return removed

    def update_by_id(self, collection: str, id: Any, update: dict) -> int:
        return self.update_many(collection, {"_id": id}, update)

    def update_one(self, collection: str, filter: dict, update: dict) -> None:
        for d in self._coll(collection):
            if self._matches(d, filter):
                self._apply_update(d, update)
                return

    def update_many(self, collection: str, filter: dict, update: dict) -> int:
        n = 0
        for d in self._coll(collection):
            if self._matches(d, filter):
                self._apply_update(d, update)
                n += 1
        return n

    def count_documents(self, collection: str, filter: dict) -> int:
        return sum(1 for d in self._coll(collection) if self._matches(d, filter))

    def drop(self, collection: str) -> None:
        self._collections.pop(collection, None)

    def health_check(self) -> dict:
        return {
            "status": "UP",
            "details": {
                "backend": "INMEMORY-MONGO",
                "collections": {
                    k: len(v) for k, v in self._collections.items()
                },
            },
        }
