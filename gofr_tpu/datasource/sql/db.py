"""SQL datasource over DB-API drivers.

Capability parity with the reference's ``datasource/sql`` (``sql.go``,
``db.go``): config-gated connection at boot with a background retry loop,
query/exec/transaction API with per-query structured logging + the
``app_sql_stats`` histogram, reflective ``select`` into dataclasses, dialect
seam, pool-stat gauges, and health check.

Driver matrix: ``sqlite`` ships in the stdlib and is the default dialect in
this environment; ``mysql``/``postgres`` use their DB-API drivers when
present and log-and-skip otherwise (the reference logs and continues when a
datasource can't connect, ``sql/sql.go:83-107``).
"""

from __future__ import annotations

import dataclasses
import re
import sqlite3
import threading
import time
from typing import Any, Optional, Sequence

from gofr_tpu.config.env import Config


class QueryLog:
    """Structured query log (reference ``sql/db.go:28-45``)."""

    def __init__(self, query: str, duration_us: int, args_count: int) -> None:
        self.type = "SQL"
        self.query = query
        self.duration = duration_us
        self.args_count = args_count

    def to_log_dict(self) -> dict:
        return {"type": self.type, "query": self.query, "duration": self.duration}

    def pretty_print(self, fp) -> None:
        fp.write(f"\x1b[38;5;8mSQL\x1b[0m {self.duration:>8}µs {self.query}\n")


class _Cursorish:
    """Shared query machinery for DB and Tx."""

    _dialect: str
    _logger: Any
    _metrics: Any

    def _execute(self, cursor, query: str, args: Sequence) -> None:
        start = time.time()
        try:
            cursor.execute(query, tuple(args))
        finally:
            elapsed_ms = (time.time() - start) * 1e3
            if self._metrics is not None:
                self._metrics.record_histogram(
                    "app_sql_stats", elapsed_ms, "type", _query_operation(query)
                )
            if self._logger is not None:
                self._logger.debug(QueryLog(query, int(elapsed_ms * 1e3), len(args)))

    def _rows_to_dicts(self, cursor) -> list[dict]:
        cols = [d[0] for d in cursor.description] if cursor.description else []
        return [dict(zip(cols, row)) for row in cursor.fetchall()]


def _query_operation(query: str) -> str:
    m = re.match(r"\s*(\w+)", query)
    return (m.group(1).upper() if m else "UNKNOWN")


class Tx(_Cursorish):
    """Transaction handle (reference ``sql/db.go:254-296``)."""

    def __init__(self, db: "DB") -> None:
        self._db = db
        self._dialect = db.dialect()
        self._logger = db._logger
        self._metrics = db._metrics
        self._conn = db._conn
        if getattr(self._conn, "needs_explicit_begin", False):
            # Autocommit connections (real mysql/postgres drivers, and the
            # dialect fakes mirroring them) open transaction blocks with an
            # explicit BEGIN; COMMIT/ROLLBACK below closes them.
            self._conn.cursor().execute("BEGIN")

    def query(self, query: str, *args) -> list[dict]:
        cur = self._conn.cursor()
        self._execute(cur, query, args)
        return self._rows_to_dicts(cur)

    def query_row(self, query: str, *args) -> Optional[dict]:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args) -> "ExecResult":
        cur = self._conn.cursor()
        self._execute(cur, query, args)
        return ExecResult(cur.rowcount, cur.lastrowid)

    def commit(self) -> None:
        self._conn.commit()
        self._db._tx_lock.release()

    def rollback(self) -> None:
        self._conn.rollback()
        self._db._tx_lock.release()


@dataclasses.dataclass
class ExecResult:
    rows_affected: int
    last_insert_id: Optional[int]


class DB(_Cursorish):
    """Connection wrapper with the reference ``container.DB`` surface
    (``container/datasources.go:14-26``)."""

    def __init__(self, conn, dialect: str, logger=None, metrics=None, database: str = "") -> None:
        self._conn = conn
        self._dialect_name = dialect
        self._logger = logger
        self._metrics = metrics
        self._database = database
        self._lock = threading.RLock()
        self._tx_lock = threading.Lock()  # serialize transactions

    # -- plain queries (reference sql/db.go:102-110) ----------------------

    def query(self, query: str, *args) -> list[dict]:
        with self._lock:
            cur = self._conn.cursor()
            self._execute(cur, query, args)
            return self._rows_to_dicts(cur)

    def query_row(self, query: str, *args) -> Optional[dict]:
        rows = self.query(query, *args)
        return rows[0] if rows else None

    def exec(self, query: str, *args) -> ExecResult:
        with self._lock:
            cur = self._conn.cursor()
            self._execute(cur, query, args)
            self._conn.commit()
            return ExecResult(cur.rowcount, cur.lastrowid)

    def begin(self) -> Tx:
        self._tx_lock.acquire()
        return Tx(self)

    # -- reflective select (reference sql/db.go:200-252) ------------------

    def select(self, target_type, query: str, *args):
        """Run ``query`` and bind rows into ``target_type``.

        ``target_type`` may be a dataclass type (→ list of instances, fields
        matched by name / ``db`` metadata key, like the reference's ``db:``
        struct tags) or ``dict`` (→ list of dicts).
        """
        rows = self.query(query, *args)
        if target_type is dict:
            return rows
        if dataclasses.is_dataclass(target_type):
            out = []
            fields = dataclasses.fields(target_type)
            colmap = {
                (f.metadata.get("db") or _to_snake(f.name)): f.name for f in fields
            }
            names = {f.name for f in fields}
            for row in rows:
                kwargs = {}
                for col, val in row.items():
                    if col in colmap:
                        kwargs[colmap[col]] = val
                    elif col in names:
                        kwargs[col] = val
                out.append(target_type(**kwargs))
            return out
        raise TypeError("select target must be a dataclass type or dict")

    # -- misc -------------------------------------------------------------

    def dialect(self) -> str:
        return self._dialect_name

    def health_check(self) -> dict:
        try:
            with self._lock:
                cur = self._conn.cursor()
                cur.execute("SELECT 1")
                cur.fetchall()
            return {
                "status": "UP",
                "details": {"dialect": self._dialect_name, "database": self._database},
            }
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        try:
            self._conn.close()
        except Exception:
            pass


def _to_snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


# Driver seam (reference idiom: sql.Open(driverName, dsn), sql.go:30-67).
# Maps dialect → connect(host, port, user, password, database) returning a
# DB-API connection. Real drivers self-register when importable; tests (and
# driverless environments) register the in-proc fakes from ``fakedb.py``.
_DRIVER_REGISTRY: dict[str, Any] = {}


def register_sql_driver(dialect: str, connect) -> None:
    """Register/override the connection factory for ``mysql``/``postgres``."""
    _DRIVER_REGISTRY[dialect.lower()] = connect


class _PyformatCursor:
    """Translates this framework's dialect bindvars (mysql ``?``, postgres
    ``$n`` — the reference drivers' styles, ``sql/query_builder.go:8-70``)
    to the ``%s`` pyformat style both pymysql and psycopg2 actually speak.
    Literal-aware: quoted SQL strings pass through untouched (a ``?`` or
    ``$1`` inside ``'...'`` is data, not a bindvar) and every raw ``%``
    is escaped to ``%%`` so pyformat can't trip on ``LIKE '%a%'``."""

    _DOLLAR = re.compile(r"\$(\d+)")
    _STRING = re.compile(r"'(?:[^']|'')*'")  # single-quoted SQL literal

    def __init__(self, cursor, dialect: str) -> None:
        self._cur = cursor
        self._dialect = dialect

    def _translate(self, query: str) -> tuple[str, list[int]]:
        order: list[int] = []

        def outside(text: str) -> str:
            text = text.replace("%", "%%")
            if self._dialect == "postgres":
                def repl(m):
                    order.append(int(m.group(1)) - 1)
                    return "%s"

                return self._DOLLAR.sub(repl, text)
            return text.replace("?", "%s")

        chunks: list[str] = []
        last = 0
        for m in self._STRING.finditer(query):
            chunks.append(outside(query[last:m.start()]))
            chunks.append(m.group(0).replace("%", "%%"))
            last = m.end()
        chunks.append(outside(query[last:]))
        return "".join(chunks), order

    def execute(self, query: str, args=()):
        args = tuple(args)
        query, order = self._translate(query)
        if self._dialect == "postgres":
            args = tuple(args[i] for i in order)  # $n may repeat/reorder
        return self._cur.execute(query, args)

    def __getattr__(self, name):
        return getattr(self._cur, name)


class _PyformatConnection:
    """Wraps a real driver connection in the dialect's bindvar style.

    Drivers run in autocommit mode (set by ``_real_driver``) so read-only
    traffic never leaves a transaction idling open; ``Tx`` issues an
    explicit ``BEGIN`` (``needs_explicit_begin``) to open real transaction
    blocks."""

    needs_explicit_begin = True

    def __init__(self, conn, dialect: str) -> None:
        self._conn = conn
        self._dialect = dialect

    def cursor(self) -> _PyformatCursor:
        return _PyformatCursor(self._conn.cursor(), self._dialect)

    def __getattr__(self, name):
        return getattr(self._conn, name)


def _real_driver(dialect: str):
    """Best-effort import of a real DB-API driver for the dialect, wrapped
    so it accepts the dialect's native bindvar style."""
    if dialect == "mysql":
        try:
            import pymysql  # type: ignore[import-not-found]

            return lambda **kw: _PyformatConnection(pymysql.connect(
                host=kw["host"], port=kw["port"], user=kw["user"],
                password=kw["password"], database=kw["database"],
                autocommit=True,
            ), "mysql")
        except ImportError:
            return None
    if dialect == "postgres":
        try:
            import psycopg2  # type: ignore[import-not-found]

            def _connect_pg(**kw):
                conn = psycopg2.connect(
                    host=kw["host"], port=kw["port"], user=kw["user"],
                    password=kw["password"], dbname=kw["database"],
                )
                # Reads must not idle in an open transaction (blocks
                # VACUUM, pins snapshots); Tx issues explicit BEGIN.
                conn.autocommit = True
                return _PyformatConnection(conn, "postgres")

            return _connect_pg
        except ImportError:
            return None
    return None


def new_sql_from_config(config: Config, logger=None, metrics=None) -> Optional[DB]:
    """Create the SQL datasource from env config (reference ``sql/sql.go:30-67``,
    config keys ``sql.go:109-118``).

    Gated on ``DB_DIALECT``: ``sqlite`` (stdlib; ``DB_NAME`` is the file path,
    default in-memory); ``mysql``/``postgres`` connect via a registered
    driver factory (:func:`register_sql_driver`) or a real DB-API driver
    when importable, reading ``DB_HOST``/``DB_PORT``/``DB_USER``/
    ``DB_PASSWORD``/``DB_NAME``.
    Returns None when unconfigured — the container treats that as "no SQL".
    """
    dialect = (config.get_or_default("DB_DIALECT", "") or "").lower()
    if not dialect:
        return None
    if dialect == "sqlite":
        path = config.get_or_default("DB_NAME", ":memory:")
        conn = sqlite3.connect(path, check_same_thread=False)
        conn.execute("PRAGMA journal_mode=WAL") if path != ":memory:" else None
        db = DB(conn, "sqlite", logger, metrics, database=path)
        if logger is not None:
            logger.infof("connected to sqlite database %s", path)
        return db
    if dialect in ("mysql", "postgres"):
        connect = _DRIVER_REGISTRY.get(dialect) or _real_driver(dialect)
        if connect is None:
            if logger is not None:
                logger.errorf(
                    "SQL dialect %s has no driver: install one (pymysql/"
                    "psycopg2) or register a factory via register_sql_driver "
                    "(in-proc fakes: datasource/sql/fakedb.py)",
                    dialect,
                )
            return None
        database = config.get_or_default("DB_NAME", "")
        try:
            conn = connect(
                host=config.get_or_default("DB_HOST", "localhost"),
                port=int(config.get_or_default(
                    "DB_PORT", "3306" if dialect == "mysql" else "5432"
                )),
                user=config.get_or_default("DB_USER", "root"),
                password=config.get_or_default("DB_PASSWORD", ""),
                database=database,
            )
        except Exception as exc:  # noqa: BLE001 — boot must not crash
            # Reference logs and continues when a datasource can't connect
            # (sql.go:83-107 retries in background; our container health
            # then reports the missing datasource).
            if logger is not None:
                logger.errorf("could not connect %s database: %s", dialect, exc)
            return None
        db = DB(conn, dialect, logger, metrics, database=database)
        if logger is not None:
            logger.infof("connected to %s database %s", dialect, database)
        return db
    if logger is not None:
        logger.errorf("unsupported DB_DIALECT %s", dialect)
    return None
