"""SQL datasource (reference: ``pkg/gofr/datasource/sql``)."""

from gofr_tpu.datasource.sql.db import (
    DB,
    Tx,
    new_sql_from_config,
    register_sql_driver,
)
from gofr_tpu.datasource.sql.query_builder import (
    delete_by_query,
    insert_query,
    select_by_query,
    select_query,
    update_by_query,
)

__all__ = [
    "DB",
    "Tx",
    "new_sql_from_config",
    "register_sql_driver",
    "insert_query",
    "select_query",
    "select_by_query",
    "update_by_query",
    "delete_by_query",
]
