"""Dialect-aware SQL query builder (reference ``sql/query_builder.go:8-70`` +
``sql/bind.go:24-51``).

Generates the CRUD statements the REST-handler generator uses, quoting
identifiers and numbering bind variables per dialect: backticks + ``?`` for
mysql/sqlite, double quotes + ``$n`` for postgres.
"""

from __future__ import annotations

from typing import Sequence


def _quote(dialect: str, ident: str) -> str:
    if dialect == "postgres":
        return f'"{ident}"'
    return f"`{ident}`"


def _bindvar(dialect: str, n: int) -> str:
    if dialect == "postgres":
        return f"${n}"
    return "?"


def insert_query(dialect: str, table: str, fields: Sequence[str]) -> str:
    cols = ", ".join(_quote(dialect, f) for f in fields)
    vals = ", ".join(_bindvar(dialect, i + 1) for i in range(len(fields)))
    return f"INSERT INTO {_quote(dialect, table)} ({cols}) VALUES ({vals})"


def select_query(dialect: str, table: str) -> str:
    return f"SELECT * FROM {_quote(dialect, table)}"


def select_by_query(dialect: str, table: str, field: str) -> str:
    return (
        f"SELECT * FROM {_quote(dialect, table)} "
        f"WHERE {_quote(dialect, field)} = {_bindvar(dialect, 1)}"
    )


def update_by_query(dialect: str, table: str, fields: Sequence[str], by: str) -> str:
    sets = ", ".join(
        f"{_quote(dialect, f)} = {_bindvar(dialect, i + 1)}" for i, f in enumerate(fields)
    )
    return (
        f"UPDATE {_quote(dialect, table)} SET {sets} "
        f"WHERE {_quote(dialect, by)} = {_bindvar(dialect, len(fields) + 1)}"
    )


def delete_by_query(dialect: str, table: str, field: str) -> str:
    return (
        f"DELETE FROM {_quote(dialect, table)} "
        f"WHERE {_quote(dialect, field)} = {_bindvar(dialect, 1)}"
    )
