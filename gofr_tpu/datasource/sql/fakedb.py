"""In-proc DB-API peers speaking the mysql / postgres SQL dialects.

The miniredis idiom (SURVEY §4: "interface-seam every external dependency
→ a fake in-process peer") applied to SQL: the reference validates its
mysql/postgres code against sqlmock + real CI containers
(``/root/reference/pkg/gofr/datasource/sql/sql_mock.go:13-33``,
``.github/workflows/go.yml:86-87``); this environment has neither driver
nor server, so these fakes make the mysql/pg dialect branches executable.

Each fake is a DB-API connection backed by in-memory sqlite that accepts
its dialect's surface syntax — the exact forms ``query_builder.py``
generates and handlers write:

* **mysql**: backtick-quoted identifiers and ``?`` bindvars (both
  sqlite-native), ``AUTO_INCREMENT`` / common column types translated in
  DDL;
* **postgres**: double-quoted identifiers (sqlite-native), ``$n``
  bindvars (→ sqlite's positional ``?n``), ``SERIAL``/``BIGSERIAL``
  translated in DDL.

Wire them into the config seam with
:func:`gofr_tpu.datasource.sql.register_sql_driver` — tests register
``connect_fake_mysql`` / ``connect_fake_postgres`` and the whole stack
(container → DB → query builder → CRUD → migrations) runs mysql/pg SQL.
"""

from __future__ import annotations

import re
import sqlite3


def _translate_mysql(query: str) -> str:
    # Backticks and ? placeholders are sqlite-native; only DDL niceties
    # need mapping. AUTO_INCREMENT only works in sqlite as the exact
    # INTEGER PRIMARY KEY AUTOINCREMENT form.
    q = re.sub(
        r"(?i)\b(?:INT|BIGINT|INTEGER)\s+PRIMARY\s+KEY\s+AUTO_INCREMENT",
        "INTEGER PRIMARY KEY AUTOINCREMENT", query,
    )
    q = re.sub(r"(?i)\s+AUTO_INCREMENT\b", "", q)
    q = re.sub(r"(?i)\bDATETIME\b", "TEXT", q)
    return q


def _translate_postgres(query: str) -> str:
    # $n → sqlite positional ?n; SERIAL pseudo-types → AUTOINCREMENT.
    q = re.sub(
        r"(?i)\b(?:BIG)?SERIAL\s+PRIMARY\s+KEY",
        "INTEGER PRIMARY KEY AUTOINCREMENT", query,
    )
    q = re.sub(r"(?i)\b(?:BIG)?SERIAL\b", "INTEGER", q)
    q = re.sub(r"(?i)\bTIMESTAMPTZ?\b", "TEXT", q)
    q = re.sub(r"\$(\d+)", r"?\1", q)
    return q


_TRANSLATORS = {"mysql": _translate_mysql, "postgres": _translate_postgres}


class _FakeCursor:
    def __init__(self, cur: sqlite3.Cursor, translate) -> None:
        self._cur = cur
        self._translate = translate

    def execute(self, query: str, args=()):  # DB-API
        return self._cur.execute(self._translate(query), tuple(args))

    def fetchall(self):
        return self._cur.fetchall()

    def fetchone(self):
        return self._cur.fetchone()

    @property
    def description(self):
        return self._cur.description

    @property
    def rowcount(self):
        return self._cur.rowcount

    @property
    def lastrowid(self):
        return self._cur.lastrowid

    def close(self) -> None:
        self._cur.close()


class FakeDialectConnection:
    """DB-API connection accepting mysql/postgres surface SQL over sqlite.

    Mirrors the real drivers' transaction semantics: autocommit outside
    explicit blocks (sqlite ``isolation_level=None``), transactions opened
    by ``Tx``'s explicit ``BEGIN`` (``needs_explicit_begin``).

    Fidelity caveat: ``lastrowid`` behaves like mysql's insert id; real
    postgres returns no insert id without ``INSERT ... RETURNING``.
    """

    needs_explicit_begin = True

    def __init__(self, dialect: str) -> None:
        if dialect not in _TRANSLATORS:
            raise ValueError(f"unsupported fake dialect {dialect!r}")
        self.dialect = dialect
        self._translate = _TRANSLATORS[dialect]
        self._conn = sqlite3.connect(
            ":memory:", check_same_thread=False, isolation_level=None
        )

    def cursor(self) -> _FakeCursor:
        return _FakeCursor(self._conn.cursor(), self._translate)

    def commit(self) -> None:
        self._conn.commit()

    def rollback(self) -> None:
        self._conn.rollback()

    def close(self) -> None:
        self._conn.close()


def connect_fake_mysql(**_kw) -> FakeDialectConnection:
    """Driver-seam factory (ignores host/port/user — in-proc)."""
    return FakeDialectConnection("mysql")


def connect_fake_postgres(**_kw) -> FakeDialectConnection:
    return FakeDialectConnection("postgres")
