"""In-process mini Redis server for tests.

The reference's test strategy stands up miniredis — a real in-process Redis —
instead of mocking the client (SURVEY §4, reference ``redis/redis_test.go:8``).
This is the same seam for this framework: a threaded TCP server speaking
enough RESP2 for the framework's usage (strings, hashes, lists, sets,
expiry, MULTI/EXEC, INFO).
"""

from __future__ import annotations

import fnmatch
import socketserver
import threading
import time
from typing import Any, Optional


class _Store:
    def __init__(self) -> None:
        self.data: dict[str, Any] = {}
        self.expiry: dict[str, float] = {}
        self.lock = threading.RLock()

    def _expired(self, key: str) -> bool:
        exp = self.expiry.get(key)
        if exp is not None and time.time() > exp:
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return True
        return False

    def get(self, key: str) -> Any:
        with self.lock:
            if self._expired(key):
                return None
            return self.data.get(key)

    def set(self, key: str, value: Any) -> None:
        with self.lock:
            self.data[key] = value
            self.expiry.pop(key, None)


def _ok() -> bytes:
    return b"+OK\r\n"


def _err(msg: str) -> bytes:
    return f"-ERR {msg}\r\n".encode()


def _int(n: int) -> bytes:
    return f":{n}\r\n".encode()


def _bulk(value: Optional[str]) -> bytes:
    if value is None:
        return b"$-1\r\n"
    data = value.encode() if isinstance(value, str) else value
    return f"${len(data)}\r\n".encode() + data + b"\r\n"


def _array(items: list) -> bytes:
    out = [f"*{len(items)}\r\n".encode()]
    for item in items:
        if isinstance(item, bytes) and (item[:1] in (b"+", b"-", b":", b"$", b"*")):
            out.append(item)
        elif isinstance(item, int):
            out.append(_int(item))
        else:
            out.append(_bulk(item))
    return b"".join(out)


class MiniRedis:
    """`start()` binds an ephemeral port; point the client at `.port`."""

    def __init__(self) -> None:
        self.store = _Store()
        self.port: int = 0
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- command handlers --------------------------------------------------

    def dispatch(self, args: list[str], conn_state: dict) -> bytes:
        cmd = args[0].upper()
        s = self.store

        if conn_state.get("multi") is not None and cmd not in ("EXEC", "MULTI", "DISCARD"):
            conn_state["multi"].append(args)
            return b"+QUEUED\r\n"

        if cmd == "PING":
            return b"+PONG\r\n"
        if cmd == "SET":
            s.set(args[1], args[2])
            if len(args) >= 5 and args[3].upper() == "EX":
                s.expiry[args[1]] = time.time() + int(args[4])
            return _ok()
        if cmd == "GET":
            val = s.get(args[1])
            if val is not None and not isinstance(val, str):
                return _err("wrong type")
            return _bulk(val)
        if cmd == "DEL":
            n = 0
            with s.lock:
                for key in args[1:]:
                    if s.data.pop(key, None) is not None:
                        n += 1
                    s.expiry.pop(key, None)
            return _int(n)
        if cmd == "EXISTS":
            n = sum(1 for key in args[1:] if s.get(key) is not None)
            return _int(n)
        if cmd == "INCR":
            with s.lock:
                val = int(s.get(args[1]) or 0) + 1
                s.set(args[1], str(val))
            return _int(val)
        if cmd == "EXPIRE":
            with s.lock:
                if s.get(args[1]) is None:
                    return _int(0)
                s.expiry[args[1]] = time.time() + int(args[2])
            return _int(1)
        if cmd == "TTL":
            with s.lock:
                if s.get(args[1]) is None:
                    return _int(-2)
                exp = s.expiry.get(args[1])
                return _int(-1 if exp is None else max(0, int(exp - time.time())))
        if cmd == "KEYS":
            with s.lock:
                keys = [k for k in list(s.data) if not s._expired(k)]
            return _array([k for k in keys if fnmatch.fnmatch(k, args[1])])
        if cmd == "HSET":
            with s.lock:
                h = s.data.setdefault(args[1], {})
                if not isinstance(h, dict):
                    return _err("wrong type")
                added = 0
                for i in range(2, len(args) - 1, 2):
                    if args[i] not in h:
                        added += 1
                    h[args[i]] = args[i + 1]
            return _int(added)
        if cmd == "HGET":
            h = s.get(args[1]) or {}
            return _bulk(h.get(args[2]) if isinstance(h, dict) else None)
        if cmd == "HGETALL":
            h = s.get(args[1]) or {}
            flat: list = []
            for k, v in (h.items() if isinstance(h, dict) else []):
                flat += [k, v]
            return _array(flat)
        if cmd == "HDEL":
            with s.lock:
                h = s.data.get(args[1]) or {}
                n = sum(1 for f in args[2:] if h.pop(f, None) is not None)
            return _int(n)
        if cmd in ("LPUSH", "RPUSH"):
            with s.lock:
                lst = s.data.setdefault(args[1], [])
                if not isinstance(lst, list):
                    return _err("wrong type")
                for v in args[2:]:
                    lst.insert(0, v) if cmd == "LPUSH" else lst.append(v)
            return _int(len(lst))
        if cmd == "LRANGE":
            lst = s.get(args[1]) or []
            start, stop = int(args[2]), int(args[3])
            stop = len(lst) if stop == -1 else stop + 1
            return _array(lst[start:stop])
        if cmd == "LPOP":
            with s.lock:
                lst = s.data.get(args[1]) or []
                return _bulk(lst.pop(0) if lst else None)
        if cmd == "SADD":
            with s.lock:
                st = s.data.setdefault(args[1], set())
                if not isinstance(st, set):
                    return _err("wrong type")
                n = 0
                for v in args[2:]:
                    if v not in st:
                        st.add(v)
                        n += 1
            return _int(n)
        if cmd == "SMEMBERS":
            st = s.get(args[1]) or set()
            return _array(sorted(st))
        if cmd == "FLUSHDB":
            with s.lock:
                s.data.clear()
                s.expiry.clear()
            return _ok()
        if cmd == "INFO":
            body = (
                "# Stats\r\ntotal_connections_received:1\r\n"
                "total_commands_processed:1\r\nkeyspace_hits:0\r\nkeyspace_misses:0\r\n"
            )
            return _bulk(body)
        if cmd == "MULTI":
            conn_state["multi"] = []
            return _ok()
        if cmd == "DISCARD":
            conn_state["multi"] = None
            return _ok()
        if cmd == "EXEC":
            queued = conn_state.get("multi") or []
            conn_state["multi"] = None
            return _array([self.dispatch(q, conn_state) for q in queued])
        return _err(f"unknown command '{args[0]}'")

    # -- server loop -------------------------------------------------------

    def start(self) -> "MiniRedis":
        mini = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                buf = b""
                state: dict = {"multi": None}
                sock = self.request
                while True:
                    try:
                        chunk = sock.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    while True:
                        parsed = _try_parse(buf)
                        if parsed is None:
                            break
                        args, buf = parsed
                        if not args:
                            continue
                        try:
                            reply = mini.dispatch(args, state)
                        except Exception as exc:
                            reply = _err(str(exc))
                        try:
                            sock.sendall(reply)
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()


def _try_parse(buf: bytes):
    """Parse one RESP command array from buf; returns (args, rest) or None."""
    if not buf:
        return None
    if not buf.startswith(b"*"):
        # inline command
        if b"\r\n" not in buf:
            return None
        line, _, rest = buf.partition(b"\r\n")
        return line.decode().split(), rest
    head, _, rest = buf.partition(b"\r\n")
    if not _:
        return None
    try:
        count = int(head[1:])
    except ValueError:
        return [], rest
    args = []
    for _i in range(count):
        if not rest.startswith(b"$"):
            return None
        size_line, sep, rest2 = rest.partition(b"\r\n")
        if not sep:
            return None
        size = int(size_line[1:])
        if len(rest2) < size + 2:
            return None
        args.append(rest2[:size].decode("utf-8", "replace"))
        rest = rest2[size + 2 :]
    return args, rest
