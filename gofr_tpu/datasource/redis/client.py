"""RESP2 Redis client.

Implements the wire protocol natively over a socket (inline arrays out,
typed replies in), with the reference's observability contract: every command
is logged with its duration and recorded in the ``app_redis_stats`` histogram
(reference ``redis/hook.go:17-21,85-105``), ping-at-boot (``redis/redis.go:60``),
and ``INFO``-based health check (``redis/health.go:13-41``).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Optional

from gofr_tpu.config.env import Config


class RedisError(Exception):
    pass


class RedisLog:
    def __init__(self, args: tuple, duration_us: int) -> None:
        self.type = "REDIS"
        self.command = " ".join(str(a) for a in args[:2])
        self.duration = duration_us

    def to_log_dict(self) -> dict:
        return {"type": self.type, "command": self.command, "duration": self.duration}

    def pretty_print(self, fp) -> None:
        fp.write(f"\x1b[38;5;8mREDIS\x1b[0m {self.duration:>8}µs {self.command}\n")


def _encode_command(args: tuple) -> bytes:
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        if isinstance(a, bytes):
            data = a
        else:
            data = str(a).encode("utf-8")
        out.append(f"${len(data)}\r\n".encode() + data + b"\r\n")
    return b"".join(out)


class _Reader:
    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._buf = b""

    def _readline(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        line, _, self._buf = self._buf.partition(b"\r\n")
        return line

    def _readexact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisError("connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2 :]
        return data

    def read_reply(self) -> Any:
        line = self._readline()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            length = int(rest)
            if length == -1:
                return None
            return self._readexact(length).decode("utf-8", "replace")
        if kind == b"*":
            count = int(rest)
            if count == -1:
                return None
            return [self.read_reply() for _ in range(count)]
        raise RedisError(f"bad reply type {kind!r}")


class Redis:
    """Thread-safe single-connection RESP client."""

    def __init__(self, host: str, port: int, logger=None, metrics=None, timeout: float = 5.0) -> None:
        self.host, self.port = host, port
        self._logger = logger
        self._metrics = metrics
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[_Reader] = None
        self._connect()

    def _connect(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._reader = _Reader(sock)

    def command(self, *args) -> Any:
        start = time.time()
        with self._lock:
            try:
                self._sock.sendall(_encode_command(args))
                reply = self._reader.read_reply()
            except (OSError, RedisError):
                # One reconnect attempt (role of go-redis's retry).
                self._connect()
                self._sock.sendall(_encode_command(args))
                reply = self._reader.read_reply()
        elapsed = time.time() - start
        if self._metrics is not None:
            self._metrics.record_histogram(
                "app_redis_stats", elapsed * 1e3, "type", str(args[0]).upper()
            )
        if self._logger is not None:
            self._logger.debug(RedisLog(args, int(elapsed * 1e6)))
        return reply

    # -- convenience commands (go-redis Cmdable subset the reference uses) --

    def ping(self) -> str:
        return self.command("PING")

    def get(self, key: str) -> Optional[str]:
        return self.command("GET", key)

    def set(self, key: str, value, ex: Optional[int] = None) -> str:
        if ex is not None:
            return self.command("SET", key, value, "EX", ex)
        return self.command("SET", key, value)

    def delete(self, *keys: str) -> int:
        return self.command("DEL", *keys)

    def exists(self, *keys: str) -> int:
        return self.command("EXISTS", *keys)

    def incr(self, key: str) -> int:
        return self.command("INCR", key)

    def expire(self, key: str, seconds: int) -> int:
        return self.command("EXPIRE", key, seconds)

    def ttl(self, key: str) -> int:
        return self.command("TTL", key)

    def keys(self, pattern: str = "*") -> list:
        return self.command("KEYS", pattern) or []

    def hset(self, key: str, *pairs) -> int:
        return self.command("HSET", key, *pairs)

    def hget(self, key: str, field: str) -> Optional[str]:
        return self.command("HGET", key, field)

    def hgetall(self, key: str) -> dict:
        flat = self.command("HGETALL", key) or []
        return dict(zip(flat[::2], flat[1::2]))

    def hdel(self, key: str, *fields: str) -> int:
        return self.command("HDEL", key, *fields)

    def lpush(self, key: str, *values) -> int:
        return self.command("LPUSH", key, *values)

    def rpush(self, key: str, *values) -> int:
        return self.command("RPUSH", key, *values)

    def lrange(self, key: str, start: int, stop: int) -> list:
        return self.command("LRANGE", key, start, stop) or []

    def sadd(self, key: str, *members) -> int:
        return self.command("SADD", key, *members)

    def smembers(self, key: str) -> list:
        return self.command("SMEMBERS", key) or []

    def flushdb(self) -> str:
        return self.command("FLUSHDB")

    def info(self, section: str = "") -> str:
        return self.command("INFO", section) if section else self.command("INFO")

    def tx_pipeline(self) -> "TxPipeline":
        """MULTI/EXEC pipeline (the reference uses TxPipelined for migrations,
        ``migration/redis.go:53-68``)."""
        return TxPipeline(self)

    # -- lifecycle ---------------------------------------------------------

    def health_check(self) -> dict:
        try:
            info = self.info("stats")
            stats = {}
            for line in (info or "").splitlines():
                if ":" in line and not line.startswith("#"):
                    k, _, v = line.partition(":")
                    stats[k] = v
            return {
                "status": "UP",
                "details": {"host": f"{self.host}:{self.port}", "stats": stats},
            }
        except Exception as exc:
            return {"status": "DOWN", "error": str(exc)}

    def close(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except Exception:
            pass


class TxPipeline:
    """Queue commands client-side, send under MULTI/EXEC on exec()."""

    def __init__(self, client: Redis) -> None:
        self._client = client
        self._commands: list[tuple] = []

    def command(self, *args) -> "TxPipeline":
        self._commands.append(args)
        return self

    def set(self, key, value):
        return self.command("SET", key, value)

    def hset(self, key, *pairs):
        return self.command("HSET", key, *pairs)

    def delete(self, *keys):
        return self.command("DEL", *keys)

    def exec(self) -> list:
        c = self._client
        with c._lock:
            c._sock.sendall(_encode_command(("MULTI",)))
            c._reader.read_reply()
            for cmd in self._commands:
                c._sock.sendall(_encode_command(cmd))
                c._reader.read_reply()  # +QUEUED
            c._sock.sendall(_encode_command(("EXEC",)))
            return c._reader.read_reply()


def new_redis_from_config(config: Config, logger=None, metrics=None) -> Optional[Redis]:
    """Config-gated creation (reference ``redis/redis.go:35-77``): requires
    ``REDIS_HOST``; ``REDIS_PORT`` defaults to 6379; pings at boot and logs
    failure without killing the app."""
    host = config.get_or_default("REDIS_HOST", "")
    if not host:
        return None
    port = int(config.get_or_default("REDIS_PORT", "6379"))
    try:
        client = Redis(host, port, logger=logger, metrics=metrics)
        client.ping()
        if logger is not None:
            logger.infof("connected to redis at %s:%d", host, port)
        return client
    except Exception as exc:
        if logger is not None:
            logger.errorf("could not connect to redis at %s:%d: %s", host, port, exc)
        return None
