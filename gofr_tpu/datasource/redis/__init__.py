"""Redis datasource (reference: ``pkg/gofr/datasource/redis``).

A from-scratch RESP2 client (the role go-redis plays in the reference) with
per-command logging + the ``app_redis_stats`` histogram (reference
``redis/hook.go:17-105``), plus :class:`MiniRedis`, an in-process RESP server
that plays the role miniredis plays in the reference's tests (SURVEY §4).
"""

from gofr_tpu.datasource.redis.client import Redis, new_redis_from_config
from gofr_tpu.datasource.redis.miniredis import MiniRedis

__all__ = ["Redis", "new_redis_from_config", "MiniRedis"]
