"""Datasources (reference: ``pkg/gofr/datasource``).

Each datasource follows the reference's integration idiom (SURVEY §1):
config-gated creation in the container, a ``health_check()`` method, metrics
hooks, and small local logger/metrics seams instead of importing the world
(reference ``datasource/logger.go:3-8`` — "accept interfaces, return
concrete types").
"""
