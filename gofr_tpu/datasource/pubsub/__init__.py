"""Pub/Sub datasource (reference: ``pkg/gofr/datasource/pubsub``).

``Message`` doubles as the request object for subscription handlers exactly
like the reference (``pubsub/message.go:8-52``): ``bind`` JSON-decodes the
payload and ``param("topic")`` returns the topic. Backends: an in-process
broker (always available; the seam the reference fills with Kafka/GCP/MQTT),
selected via ``PUBSUB_BACKEND`` (reference ``container/container.go:85-130``).
External brokers log-and-skip when their clients aren't present.
"""

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog
from gofr_tpu.datasource.pubsub.inproc import InProcBroker

__all__ = ["Message", "PubSubLog", "InProcBroker", "new_pubsub_from_config"]


def new_pubsub_from_config(config, logger=None, metrics=None):
    """Backend switch (reference ``container/container.go:85-130``)."""
    backend = (config.get_or_default("PUBSUB_BACKEND", "") or "").upper()
    if not backend:
        return None
    if backend == "INPROC":
        return InProcBroker(logger=logger, metrics=metrics)
    if backend in ("KAFKA", "GOOGLE", "MQTT"):
        if logger is not None:
            logger.errorf(
                "PUBSUB_BACKEND=%s requires an external client library not "
                "present in this environment; use INPROC or install the client",
                backend,
            )
        return None
    if logger is not None:
        logger.errorf("unsupported PUBSUB_BACKEND %s", backend)
    return None
