"""Pub/Sub datasource (reference: ``pkg/gofr/datasource/pubsub``).

``Message`` doubles as the request object for subscription handlers exactly
like the reference (``pubsub/message.go:8-52``): ``bind`` JSON-decodes the
payload and ``param("topic")`` returns the topic. Backends, selected via
``PUBSUB_BACKEND`` (reference ``container/container.go:85-130``):

* ``INPROC`` — always-available in-process broker (tests/examples/offline
  batch path);
* ``MQTT`` — dependency-free MQTT 3.1.1 wire-protocol client
  (``mqtt.py``); tested against ``testutil.mqtt_broker``;
* ``KAFKA`` / ``GOOGLE`` — clients written against driver seams
  (``kafka.py`` / ``google.py``); they raise
  :class:`PubSubBackendUnavailable` when the driver library isn't
  installed, mirroring how the reference's CI gates broker tests on
  service containers (SURVEY §4).
"""

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog
from gofr_tpu.datasource.pubsub.inproc import InProcBroker
from gofr_tpu.datasource.pubsub.kafka import (
    KafkaClient,
    PubSubBackendUnavailable,
    new_kafka_from_config,
)
from gofr_tpu.datasource.pubsub.google import (
    GooglePubSubClient,
    new_google_from_config,
)
from gofr_tpu.datasource.pubsub.mqtt import MQTTClient, new_mqtt_from_config

__all__ = [
    "Message",
    "PubSubLog",
    "InProcBroker",
    "MQTTClient",
    "KafkaClient",
    "GooglePubSubClient",
    "PubSubBackendUnavailable",
    "new_pubsub_from_config",
]


def new_pubsub_from_config(config, logger=None, metrics=None):
    """Backend switch (reference ``container/container.go:85-130``)."""
    backend = (config.get_or_default("PUBSUB_BACKEND", "") or "").upper()
    if not backend:
        return None
    try:
        if backend == "INPROC":
            return InProcBroker(logger=logger, metrics=metrics)
        if backend == "MQTT":
            return new_mqtt_from_config(config, logger=logger, metrics=metrics)
        if backend == "KAFKA":
            return new_kafka_from_config(config, logger=logger, metrics=metrics)
        if backend == "GOOGLE":
            return new_google_from_config(config, logger=logger, metrics=metrics)
    except Exception as exc:  # noqa: BLE001
        # Boot must not crash on a missing driver/broker, malformed numeric
        # config, or driver-native connect errors (kafka NoBrokersAvailable,
        # google DefaultCredentialsError, …) — log and run without pub/sub,
        # like the reference logs datasource connect errors and continues.
        if logger is not None:
            logger.errorf("pub/sub backend %s unavailable: %s", backend, exc)
        return None
    if logger is not None:
        logger.errorf("unsupported PUBSUB_BACKEND %s", backend)
    return None
