"""Google Cloud Pub/Sub backend behind a driver seam.

Reference: ``pkg/gofr/datasource/pubsub/google`` — GCP client with topic
auto-create on publish/subscribe (``google.go:73-113``), subscription name
``${SUB}-${topic}`` auto-created per topic (``google.go:115-166``), receive
callback delivering one message per ``Subscribe`` call
(``google.go:168-205``).

Like the Kafka port, the client is written against a small seam
(:class:`GooglePubSubDriver`): the default factory wires it from
``google-cloud-pubsub`` when importable, otherwise raises
:class:`PubSubBackendUnavailable`; tests inject an in-memory fake.
"""

from __future__ import annotations

from typing import Optional, Protocol

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog
from gofr_tpu.datasource.pubsub.kafka import PubSubBackendUnavailable


class GooglePubSubDriver(Protocol):
    """Thin driver surface the client needs (auto-create included)."""

    def ensure_topic(self, topic: str) -> None: ...
    def ensure_subscription(self, topic: str, subscription: str) -> None: ...
    def publish(self, topic: str, value: bytes) -> None: ...
    def pull_one(
        self, subscription: str, timeout: float
    ) -> Optional[tuple[bytes, "object"]]:
        """Return (value, ack_handle) or None on timeout."""
    def ack(self, subscription: str, ack_handle: "object") -> None: ...
    def delete_topic(self, topic: str) -> None: ...
    def ping(self) -> bool: ...
    def close(self) -> None: ...


class GooglePubSubClient:
    def __init__(
        self,
        driver: GooglePubSubDriver,
        subscription_name: str = "gofr-tpu",
        project: str = "",
        logger=None,
        metrics=None,
    ) -> None:
        self._driver = driver
        self._sub_name = subscription_name
        self._project = project
        self._logger = logger
        self._metrics = metrics
        self._known_topics: set[str] = set()
        self._known_subs: set[str] = set()

    def _sub_for(self, topic: str) -> str:
        # Reference naming: ${SUBSCRIPTION}-${topic} (google.go:115-166).
        return f"{self._sub_name}-{topic}"

    def _ensure(self, topic: str, with_sub: bool) -> None:
        if topic not in self._known_topics:
            self._driver.ensure_topic(topic)
            self._known_topics.add(topic)
        if with_sub:
            sub = self._sub_for(topic)
            if sub not in self._known_subs:
                self._driver.ensure_subscription(topic, sub)
                self._known_subs.add(sub)

    # -- Publisher ----------------------------------------------------------

    def publish(self, topic: str, message: bytes) -> None:
        if isinstance(message, str):
            message = message.encode()
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_total_count", "topic", topic
            )
        self._ensure(topic, with_sub=False)
        self._driver.publish(topic, message)
        if self._logger is not None:
            self._logger.debug(PubSubLog("PUB", topic, message, host=self._project))
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_success_count", "topic", topic
            )

    # -- Subscriber ---------------------------------------------------------

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Optional[Message]:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_subscribe_total_count", "topic", topic
            )
        self._ensure(topic, with_sub=True)
        sub = self._sub_for(topic)
        got = self._driver.pull_one(sub, timeout if timeout is not None else 0.5)
        if got is None:
            return None
        value, handle = got
        if self._logger is not None:
            self._logger.debug(PubSubLog("SUB", topic, value, host=self._project))

        def _commit() -> None:
            self._driver.ack(sub, handle)
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_pubsub_subscribe_success_count", "topic", topic
                )

        return Message(topic=topic, value=value, committer=_commit)

    # -- topic admin --------------------------------------------------------

    def create_topic(self, name: str) -> None:
        self._ensure(name, with_sub=False)

    def delete_topic(self, name: str) -> None:
        self._driver.delete_topic(name)
        self._known_topics.discard(name)

    # -- lifecycle ----------------------------------------------------------

    def health_check(self) -> dict:
        up = False
        try:
            up = self._driver.ping()
        except Exception:  # noqa: BLE001
            pass
        return {
            "status": "UP" if up else "DOWN",
            "details": {"backend": "GOOGLE", "project": self._project},
        }

    def close(self) -> None:
        self._driver.close()


def new_google_from_config(config, logger=None, metrics=None) -> GooglePubSubClient:
    """Wire the real google-cloud-pubsub driver (GOOGLE_PROJECT_ID,
    GOOGLE_SUBSCRIPTION_NAME)."""
    try:
        from google.cloud import pubsub_v1  # type: ignore[import-not-found]
    except ImportError as exc:
        raise PubSubBackendUnavailable(
            "PUBSUB_BACKEND=GOOGLE needs the 'google-cloud-pubsub' driver, "
            "which is not installed in this environment. Use "
            "PUBSUB_BACKEND=INPROC or MQTT, or inject a custom client."
        ) from exc

    project = config.get_or_default("GOOGLE_PROJECT_ID", "")
    sub_name = config.get_or_default("GOOGLE_SUBSCRIPTION_NAME", "gofr-tpu")
    publisher = pubsub_v1.PublisherClient()
    subscriber = pubsub_v1.SubscriberClient()

    from google.api_core import exceptions as gexc  # type: ignore

    class _Driver:
        # Swallow ONLY AlreadyExists: the client caches ensured topics/
        # subscriptions, so a transient connection failure swallowed here
        # would never be retried — creation must raise to stay uncached.
        def ensure_topic(self, topic):
            path = publisher.topic_path(project, topic)
            try:
                publisher.create_topic(name=path)
            except gexc.AlreadyExists:
                pass

        def ensure_subscription(self, topic, subscription):
            try:
                subscriber.create_subscription(
                    name=subscriber.subscription_path(project, subscription),
                    topic=publisher.topic_path(project, topic),
                )
            except gexc.AlreadyExists:
                pass

        def publish(self, topic, value):
            publisher.publish(publisher.topic_path(project, topic), value).result(10)

        def pull_one(self, subscription, timeout):
            from google.api_core import exceptions as gexc  # type: ignore

            try:
                resp = subscriber.pull(
                    subscription=subscriber.subscription_path(project, subscription),
                    max_messages=1,
                    timeout=timeout,
                )
            except (gexc.DeadlineExceeded, gexc.RetryError):
                # An empty poll surfaces as a deadline error, not an empty
                # response — map it to the documented None-on-timeout.
                return None
            if not resp.received_messages:
                return None
            rm = resp.received_messages[0]
            return rm.message.data, rm.ack_id

        def ack(self, subscription, ack_handle):
            subscriber.acknowledge(
                subscription=subscriber.subscription_path(project, subscription),
                ack_ids=[ack_handle],
            )

        def delete_topic(self, topic):
            publisher.delete_topic(topic=publisher.topic_path(project, topic))

        def ping(self):
            # Real round trip: listing one topic exercises auth + network.
            # GAPIC signature: page_size must ride inside the request dict.
            try:
                next(
                    iter(publisher.list_topics(
                        request={"project": f"projects/{project}", "page_size": 1},
                        timeout=2.0,
                    )),
                    None,
                )
                return True
            except Exception:  # noqa: BLE001 — any driver error means DOWN
                return False

        def close(self):
            subscriber.close()

    return GooglePubSubClient(
        _Driver(), subscription_name=sub_name, project=project,
        logger=logger, metrics=metrics,
    )
