"""Message model + shared pub/sub logging.

Reference: ``pubsub/message.go:8-52`` (Message implements the Request
interface so subscription handlers get a normal Context) and
``pubsub/log.go:8-22`` (shared PUB/SUB structured log).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Optional


class Message:
    """A consumed message; presented to handlers as the Request."""

    def __init__(
        self,
        topic: str,
        value: bytes,
        metadata: Optional[dict] = None,
        committer: Optional[Callable[[], None]] = None,
    ) -> None:
        self.topic = topic
        self.value = value
        self.metadata = metadata or {}
        self._committer = committer
        self.committed = False

    # -- Request interface (reference message.go:30-52) -------------------

    def param(self, key: str) -> str:
        if key == "topic":
            return self.topic
        return str(self.metadata.get(key, ""))

    def path_param(self, key: str) -> str:
        return self.param(key)

    @property
    def body(self) -> bytes:
        return self.value

    def json(self) -> Any:
        return json.loads(self.value or b"null")

    def bind(self, target: Any) -> Any:
        from gofr_tpu.http.request import _fill

        data = self.json()
        if not isinstance(data, dict):
            raise ValueError("message body is not a JSON object")
        return _fill(target, data)

    def host_name(self) -> str:
        return ""

    def commit(self) -> None:
        """Ack the message after successful handling
        (reference ``subscriber.go:51-52`` → ``kafka/message.go:26-31``)."""
        if self._committer is not None and not self.committed:
            self._committer()
        self.committed = True


class PubSubLog:
    """Structured PUB/SUB log line (reference ``pubsub/log.go:8-22``)."""

    def __init__(self, mode: str, topic: str, value: bytes, host: str = "inproc") -> None:
        self.mode = mode  # "PUB" or "SUB"
        self.topic = topic
        self.value = value[:128].decode("utf-8", "replace")
        self.host = host

    def to_log_dict(self) -> dict:
        return {
            "mode": self.mode,
            "topic": self.topic,
            "host": self.host,
            "value": self.value,
        }

    def pretty_print(self, fp) -> None:
        fp.write(f"\x1b[38;5;8m{self.mode}\x1b[0m topic={self.topic} {self.value}\n")
