"""MQTT pub/sub backend — a dependency-free MQTT 3.1.1 client.

Reference: ``pkg/gofr/datasource/pubsub/mqtt/mqtt.go`` (paho-based client:
per-topic buffered channels ``mqtt.go:30-53``, QoS/order/retain config
``:57-78``, extended API ``SubscribeWithFunction``/``Unsubscribe``/
``Disconnect``/``Ping`` ``:233-335``). This environment has no MQTT driver
library, so the client speaks the MQTT 3.1.1 wire protocol directly over a
TCP socket — CONNECT/CONNACK, PUBLISH (QoS 0/1), PUBACK, SUBSCRIBE/SUBACK,
UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP, DISCONNECT.

At-least-once semantics: inbound QoS-1 PUBLISHes are acked on
``Message.commit()`` (the handler-succeeded ack the reference implements
with Kafka commits, ``subscriber.go:51-52``), not on receipt.

``gofr_tpu.testutil.mqtt_broker.InProcMQTTBroker`` is the in-process server
used by tests — the miniredis of this backend (SURVEY §4).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Callable, Optional

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog

# Packet types (<<4 in the fixed header).
CONNECT, CONNACK = 1, 2
PUBLISH, PUBACK = 3, 4
SUBSCRIBE, SUBACK = 8, 9
UNSUBSCRIBE, UNSUBACK = 10, 11
PINGREQ, PINGRESP = 12, 13
DISCONNECT = 14


def encode_varint(n: int) -> bytes:
    """MQTT 'remaining length' variable-byte integer."""
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | 0x80 if n else byte)
        if not n:
            return bytes(out)


def decode_varint(read: Callable[[int], bytes]) -> int:
    mult, value = 1, 0
    for _ in range(4):
        (byte,) = read(1)
        value += (byte & 0x7F) * mult
        if not byte & 0x80:
            return value
        mult *= 128
    raise ValueError("malformed remaining-length varint")


def encode_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def topic_matches(filter_: str, topic: str) -> bool:
    """MQTT topic-filter matching with ``+`` and ``#`` wildcards."""
    fparts, tparts = filter_.split("/"), topic.split("/")
    for i, fp in enumerate(fparts):
        if fp == "#":
            return True
        if i >= len(tparts):
            return False
        if fp != "+" and fp != tparts[i]:
            return False
    return len(fparts) == len(tparts)


class _Packet:
    __slots__ = ("ptype", "flags", "payload")

    def __init__(self, ptype: int, flags: int, payload: bytes) -> None:
        self.ptype, self.flags, self.payload = ptype, flags, payload


def read_packet(sock: socket.socket) -> Optional[_Packet]:
    """Read one MQTT control packet; None on clean EOF."""

    def readn(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("MQTT peer closed")
            buf += chunk
        return buf

    try:
        first = sock.recv(1)
    except OSError:
        return None
    if not first:
        return None
    length = decode_varint(readn)
    return _Packet(first[0] >> 4, first[0] & 0x0F, readn(length) if length else b"")


def write_packet(
    sock: socket.socket, ptype: int, payload: bytes, flags: int = 0
) -> None:
    sock.sendall(
        bytes([(ptype << 4) | flags]) + encode_varint(len(payload)) + payload
    )


class MQTTClient:
    """Blocking MQTT 3.1.1 client exposing the framework pub/sub surface.

    Config keys mirror the reference (``mqtt.go:57-78``): MQTT_HOST,
    MQTT_PORT, MQTT_CLIENT_ID, MQTT_QOS (0|1), MQTT_KEEP_ALIVE (seconds).
    The reference falls back to a public broker when no host is configured
    (``mqtt.go:19-22``); here the fallback is localhost:1883 — this image
    has no egress, pointing at a public broker would only hang.
    """

    def __init__(
        self,
        host: str = "localhost",
        port: int = 1883,
        client_id: str = "gofr-tpu",
        qos: int = 1,
        keep_alive: int = 30,
        logger=None,
        metrics=None,
        connect_timeout: float = 5.0,
    ) -> None:
        self.host, self.port = host, int(port)
        self.client_id = client_id
        self.qos = int(qos)
        self.keep_alive = int(keep_alive)
        self._logger = logger
        self._metrics = metrics
        self._sock = socket.create_connection((host, self.port), connect_timeout)
        # create_connection leaves the connect timeout on the socket; the
        # reader thread must block indefinitely or it dies on idle links.
        self._sock.settimeout(None)
        self._write_lock = threading.Lock()
        self._packet_id = 0
        self._pid_lock = threading.Lock()
        self._acks: dict[int, threading.Event] = {}
        # Per-topic-filter inbound queues (reference's buffered chans,
        # mqtt.go:30-53) + optional callback subscriptions.
        self._queues: dict[str, queue.Queue] = {}
        self._callbacks: dict[str, Callable[[Message], None]] = {}
        self._sub_lock = threading.Lock()
        self._pong = threading.Event()
        self._closed = False

        self._connect()
        self._reader = threading.Thread(
            target=self._read_loop, name="mqtt-reader", daemon=True
        )
        self._reader.start()
        # Callbacks run off-reader so handlers may publish (QoS-1 publish
        # waits for a PUBACK only the reader thread can process).
        self._cb_queue: queue.Queue = queue.Queue()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="mqtt-dispatch", daemon=True
        )
        self._dispatcher.start()
        if self.keep_alive > 0:
            threading.Thread(
                target=self._keepalive_loop, name="mqtt-keepalive", daemon=True
            ).start()

    # -- wire ---------------------------------------------------------------

    def _next_pid(self) -> int:
        with self._pid_lock:
            self._packet_id = self._packet_id % 65535 + 1
            return self._packet_id

    def _connect(self) -> None:
        var = encode_str("MQTT") + bytes([4]) + bytes([0x02])  # clean session
        var += struct.pack(">H", self.keep_alive)
        write_packet(self._sock, CONNECT, var + encode_str(self.client_id))
        pkt = read_packet(self._sock)
        if pkt is None or pkt.ptype != CONNACK or pkt.payload[1] != 0:
            raise ConnectionError(
                f"MQTT CONNACK refused: {pkt.payload[1] if pkt else 'EOF'}"
            )

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                pkt = read_packet(self._sock)
            except (ConnectionError, OSError):
                pkt = None
            if pkt is None:
                # Transient socket death must not silently end the
                # subscription world (the reference's paho client
                # auto-reconnects and re-subscribes): reconnect with backoff
                # and replay SUBSCRIBEs for every registered filter.
                if self._closed or not self._reconnect():
                    return
                continue
            if pkt.ptype == PUBLISH:
                self._on_publish(pkt)
            elif pkt.ptype in (PUBACK, SUBACK, UNSUBACK):
                (pid,) = struct.unpack(">H", pkt.payload[:2])
                ev = self._acks.pop(pid, None)
                if ev is not None:
                    ev.set()
            elif pkt.ptype == PINGRESP:
                self._pong.set()

    def _reconnect(self) -> bool:
        """Re-dial + CONNECT + replay SUBSCRIBEs. Runs on the reader thread,
        so re-subscribes are fire-and-forget (the reader can't wait on its
        own SUBACK processing). Retries with backoff until closed."""
        import time as _time

        try:
            self._sock.close()
        except OSError:
            pass
        delay = 0.2
        while not self._closed:
            try:
                sock = socket.create_connection((self.host, self.port), 5.0)
                sock.settimeout(None)
                self._sock = sock
                self._connect()
                with self._sub_lock:
                    topics = set(self._queues) | set(self._callbacks)
                for t in topics:
                    pid = self._next_pid()
                    payload = (
                        struct.pack(">H", pid) + encode_str(t) + bytes([self.qos])
                    )
                    with self._write_lock:
                        write_packet(self._sock, SUBSCRIBE, payload, flags=0x02)
                if self._logger is not None:
                    self._logger.infof(
                        "mqtt reconnected to %s:%d (%d subscriptions replayed)",
                        self.host, self.port, len(topics),
                    )
                return True
            except OSError:
                _time.sleep(delay)
                delay = min(delay * 2, 5.0)
        return False

    def _on_publish(self, pkt: _Packet) -> None:
        qos = (pkt.flags >> 1) & 0x03
        (tlen,) = struct.unpack(">H", pkt.payload[:2])
        topic = pkt.payload[2 : 2 + tlen].decode("utf-8")
        rest = pkt.payload[2 + tlen :]
        pid = 0
        if qos:
            (pid,) = struct.unpack(">H", rest[:2])
            rest = rest[2:]

        def _commit(pid=pid, qos=qos) -> None:
            if qos:
                with self._write_lock:
                    write_packet(self._sock, PUBACK, struct.pack(">H", pid))
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_pubsub_subscribe_success_count", "topic", topic
                )

        msg = Message(
            topic=topic, value=rest, metadata={"qos": str(qos)}, committer=_commit
        )
        if self._logger is not None:
            self._logger.debug(PubSubLog("SUB", topic, rest, host=self.host))
        # Deliver to EVERY matching subscription (overlapping filters each
        # get the message, like the reference's per-topic channels).
        with self._sub_lock:
            cbs = [f for flt, f in self._callbacks.items() if topic_matches(flt, topic)]
            qs = [q for flt, q in self._queues.items() if topic_matches(flt, topic)]
        for cb in cbs:
            self._cb_queue.put((cb, msg))
        for q in qs:
            q.put(msg)

    def _dispatch_loop(self) -> None:
        while not self._closed:
            try:
                cb, msg = self._cb_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                cb(msg)
            except Exception:  # noqa: BLE001 — handler errors must not kill dispatch
                if self._logger is not None:
                    self._logger.errorf("mqtt callback for %s raised", msg.topic)

    def _keepalive_loop(self) -> None:
        import time as _time

        interval = max(self.keep_alive / 2.0, 1.0)
        while not self._closed:
            _time.sleep(interval)
            if self._closed:
                return
            try:
                with self._write_lock:
                    write_packet(self._sock, PINGREQ, b"")
            except OSError:
                return

    def _register_ack(self, pid: int) -> threading.Event:
        """Must be called BEFORE the packet is written, or a fast broker's
        ack can race the registration and be dropped. The caller holds the
        returned event (the reader thread pops it from the dict on ack)."""
        ev = self._acks[pid] = threading.Event()
        return ev

    def _await_ack(self, ev: threading.Event, pid: int, timeout: float = 5.0) -> None:
        if not ev.wait(timeout):
            self._acks.pop(pid, None)
            raise TimeoutError(f"MQTT ack for packet {pid} timed out")

    # -- Publisher ----------------------------------------------------------

    def publish(self, topic: str, message: bytes) -> None:
        if isinstance(message, str):
            message = message.encode()
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_total_count", "topic", topic
            )
        var = encode_str(topic)
        pid, ev = 0, None
        if self.qos:
            pid = self._next_pid()
            var += struct.pack(">H", pid)
            ev = self._register_ack(pid)
        with self._write_lock:
            write_packet(self._sock, PUBLISH, var + message, flags=self.qos << 1)
        if ev is not None:
            self._await_ack(ev, pid)
        if self._logger is not None:
            self._logger.debug(PubSubLog("PUB", topic, message, host=self.host))
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_success_count", "topic", topic
            )

    # -- Subscriber ---------------------------------------------------------

    def _send_subscribe(self, topic: str) -> None:
        pid = self._next_pid()
        ev = self._register_ack(pid)
        payload = struct.pack(">H", pid) + encode_str(topic) + bytes([self.qos])
        with self._write_lock:
            write_packet(self._sock, SUBSCRIBE, payload, flags=0x02)
        self._await_ack(ev, pid)

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Optional[Message]:
        """Blocking poll for one message on ``topic`` (subscribes lazily)."""
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_subscribe_total_count", "topic", topic
            )
        with self._sub_lock:
            q = self._queues.get(topic)
            new = q is None
            if new:
                q = self._queues[topic] = queue.Queue()
        if new:
            try:
                self._send_subscribe(topic)
            except Exception:
                # Roll back the registration: leaving it would make every
                # retry see new=False and poll a queue the broker never
                # heard about — silent permanent message loss (ADVICE r1).
                with self._sub_lock:
                    self._queues.pop(topic, None)
                raise
        try:
            return q.get(timeout=timeout if timeout is not None else 0.5)
        except queue.Empty:
            return None

    def subscribe_with_function(
        self, topic: str, fn: Callable[[Message], None]
    ) -> None:
        """Callback-per-message subscription (reference ``mqtt.go:233-258``)."""
        with self._sub_lock:
            had = topic in self._callbacks
            prev = self._callbacks.get(topic)
            self._callbacks[topic] = fn
        try:
            self._send_subscribe(topic)
        except Exception:
            with self._sub_lock:  # roll back so a retry re-sends SUBSCRIBE
                if had:
                    self._callbacks[topic] = prev
                else:
                    self._callbacks.pop(topic, None)
            raise

    def unsubscribe(self, topic: str) -> None:
        pid = self._next_pid()
        ev = self._register_ack(pid)
        with self._write_lock:
            write_packet(
                self._sock, UNSUBSCRIBE, struct.pack(">H", pid) + encode_str(topic),
                flags=0x02,
            )
        self._await_ack(ev, pid)
        with self._sub_lock:
            self._queues.pop(topic, None)
            self._callbacks.pop(topic, None)

    # -- topic admin (inproc parity; MQTT topics need no creation) ----------

    def create_topic(self, name: str) -> None:  # noqa: ARG002 — broker-side no-op
        return None

    def delete_topic(self, name: str) -> None:  # noqa: ARG002
        return None

    # -- lifecycle ----------------------------------------------------------

    def ping(self, timeout: float = 5.0) -> bool:
        """PINGREQ/PINGRESP round trip (reference ``mqtt.go:282``)."""
        self._pong.clear()
        with self._write_lock:
            write_packet(self._sock, PINGREQ, b"")
        return self._pong.wait(timeout)

    def health_check(self) -> dict:
        up = False
        try:
            up = self.ping(timeout=1.0)
        except OSError:
            pass
        return {
            "status": "UP" if up else "DOWN",
            "details": {"backend": "MQTT", "host": f"{self.host}:{self.port}"},
        }

    def disconnect(self) -> None:
        self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            with self._write_lock:
                write_packet(self._sock, DISCONNECT, b"")
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def new_mqtt_from_config(config, logger=None, metrics=None) -> MQTTClient:
    return MQTTClient(
        host=config.get_or_default("MQTT_HOST", "localhost"),
        port=int(config.get_or_default("MQTT_PORT", "1883")),
        client_id=config.get_or_default("MQTT_CLIENT_ID", "gofr-tpu"),
        qos=int(config.get_or_default("MQTT_QOS", "1")),
        keep_alive=int(config.get_or_default("MQTT_KEEP_ALIVE", "30")),
        logger=logger,
        metrics=metrics,
    )
