"""In-process pub/sub broker.

The always-available backend: per-topic FIFO queues with at-least-once
delivery (messages are re-queued if not committed — the offset/commit
semantics the reference gets from Kafka consumer groups,
``kafka/message.go:26-31``). Used by examples, tests, and the offline batch
inference path (SURVEY §2.6 "offline batch path").
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog


class InProcBroker:
    def __init__(self, logger=None, metrics=None) -> None:
        self._logger = logger
        self._metrics = metrics
        self._topics: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._closed = False

    def _queue(self, topic: str) -> queue.Queue:
        with self._lock:
            q = self._topics.get(topic)
            if q is None:
                q = queue.Queue()
                self._topics[topic] = q
            return q

    # -- Publisher (reference pubsub/interface.go:11-14) -------------------

    def publish(self, topic: str, message: bytes) -> None:
        if isinstance(message, str):
            message = message.encode()
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_total_count", "topic", topic
            )
        self._queue(topic).put(message)
        if self._logger is not None:
            self._logger.debug(PubSubLog("PUB", topic, message))
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_success_count", "topic", topic
            )

    # -- Subscriber (reference pubsub/interface.go:16-20) ------------------

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Optional[Message]:
        """Blocking poll for one message; None on timeout/close."""
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_subscribe_total_count", "topic", topic
            )
        q = self._queue(topic)
        try:
            value = q.get(timeout=timeout if timeout is not None else 0.5)
        except queue.Empty:
            return None
        if self._logger is not None:
            self._logger.debug(PubSubLog("SUB", topic, value))

        def _commit() -> None:
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_pubsub_subscribe_success_count", "topic", topic
                )

        return Message(topic=topic, value=value, committer=_commit)

    # -- topic admin (used by migrations, reference migration/pubsub.go) ---

    def create_topic(self, name: str) -> None:
        self._queue(name)

    def delete_topic(self, name: str) -> None:
        with self._lock:
            self._topics.pop(name, None)

    def topics(self) -> list[str]:
        with self._lock:
            return list(self._topics)

    # -- lifecycle ---------------------------------------------------------

    def health_check(self) -> dict:
        with self._lock:
            return {
                "status": "UP",
                "details": {
                    "backend": "INPROC",
                    "topics": {t: q.qsize() for t, q in self._topics.items()},
                },
            }

    def close(self) -> None:
        self._closed = True
