"""Kafka pub/sub backend behind driver-interface seams.

Reference: ``pkg/gofr/datasource/pubsub/kafka`` — single shared writer,
per-topic readers in a mutex-guarded map (``kafka.go:23-28,45-96``),
consumer-group offsets committed after successful handling
(``message.go:26-31``), topic admin via the controller connection
(``kafka.go:204-235``), health = controller ping + stats (``health.go:9-26``).

The reference builds on a driver library (segmentio/kafka-go) and tests by
mocking the ``Reader``/``Writer``/``Connection`` interfaces
(``kafka/interfaces.go:9-24``, SURVEY §4); this port does the same: the
client is written against :class:`Reader`/:class:`Writer`/:class:`Admin`
protocols, the default factory wires them from ``kafka-python`` when that
driver is importable, and tests inject in-memory fakes. No driver is baked
into this image, so constructing the client without one raises
:class:`PubSubBackendUnavailable` with guidance instead of failing deep in
an import.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Protocol

from gofr_tpu.datasource.pubsub.base import Message, PubSubLog


class PubSubBackendUnavailable(RuntimeError):
    """Raised when a broker backend's driver library is not installed."""


class Writer(Protocol):
    def write(self, topic: str, value: bytes) -> None: ...
    def close(self) -> None: ...


class Reader(Protocol):
    def read(self, timeout: Optional[float]) -> Optional[tuple[bytes, Callable[[], None]]]:
        """Return (value, commit_fn) or None on timeout."""
    def close(self) -> None: ...


class Admin(Protocol):
    def create_topic(self, name: str) -> None: ...
    def delete_topic(self, name: str) -> None: ...
    def ping(self) -> bool: ...


class KafkaClient:
    """Framework pub/sub surface over injected Reader/Writer/Admin."""

    def __init__(
        self,
        writer: Writer,
        reader_factory: Callable[[str], Reader],
        admin: Admin,
        brokers: str = "",
        logger=None,
        metrics=None,
    ) -> None:
        self._writer = writer
        self._reader_factory = reader_factory
        self._admin = admin
        self._brokers = brokers
        self._logger = logger
        self._metrics = metrics
        # Per-topic readers, created lazily (reference kafka.go:23-28 keeps
        # them in a mutex-guarded map).
        self._readers: dict[str, Reader] = {}
        self._lock = threading.Lock()

    # -- Publisher ----------------------------------------------------------

    def publish(self, topic: str, message: bytes) -> None:
        if isinstance(message, str):
            message = message.encode()
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_total_count", "topic", topic
            )
        self._writer.write(topic, message)
        if self._logger is not None:
            self._logger.debug(PubSubLog("PUB", topic, message, host=self._brokers))
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_publish_success_count", "topic", topic
            )

    # -- Subscriber ---------------------------------------------------------

    def _reader(self, topic: str) -> Reader:
        with self._lock:
            r = self._readers.get(topic)
            if r is None:
                r = self._readers[topic] = self._reader_factory(topic)
            return r

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Optional[Message]:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_pubsub_subscribe_total_count", "topic", topic
            )
        got = self._reader(topic).read(timeout if timeout is not None else 0.5)
        if got is None:
            return None
        value, commit_fn = got
        if self._logger is not None:
            self._logger.debug(PubSubLog("SUB", topic, value, host=self._brokers))

        def _commit() -> None:
            commit_fn()
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_pubsub_subscribe_success_count", "topic", topic
                )

        return Message(topic=topic, value=value, committer=_commit)

    # -- topic admin (reference kafka.go:204-235) ---------------------------

    def create_topic(self, name: str) -> None:
        self._admin.create_topic(name)

    def delete_topic(self, name: str) -> None:
        self._admin.delete_topic(name)

    # -- lifecycle ----------------------------------------------------------

    def health_check(self) -> dict:
        up = False
        try:
            up = self._admin.ping()
        except Exception:  # noqa: BLE001 — any driver error means DOWN
            pass
        return {
            "status": "UP" if up else "DOWN",
            "details": {
                "backend": "KAFKA",
                "brokers": self._brokers,
                "readers": sorted(self._readers),
            },
        }

    def close(self) -> None:
        with self._lock:
            readers, self._readers = list(self._readers.values()), {}
        for r in readers:
            r.close()
        self._writer.close()
        if hasattr(self._admin, "close"):
            self._admin.close()


def new_kafka_from_config(config, logger=None, metrics=None) -> KafkaClient:
    """Build a KafkaClient from env config using the kafka-python driver.

    Config keys mirror the reference (``kafka.go:45-96``): KAFKA_BROKER,
    KAFKA_CONSUMER_GROUP, KAFKA_OFFSET (earliest|latest).
    """
    try:
        from kafka import KafkaAdminClient, KafkaConsumer, KafkaProducer
        from kafka.admin import NewTopic
    except ImportError as exc:
        raise PubSubBackendUnavailable(
            "PUBSUB_BACKEND=KAFKA needs the 'kafka-python' driver, which is "
            "not installed in this environment. Use PUBSUB_BACKEND=INPROC or "
            "MQTT, or inject a custom client via app.use_pubsub(...)."
        ) from exc

    brokers = config.get_or_default("KAFKA_BROKER", "localhost:9092")
    group = config.get_or_default("KAFKA_CONSUMER_GROUP", "gofr-tpu")
    offset = config.get_or_default("KAFKA_OFFSET", "earliest")

    producer = KafkaProducer(bootstrap_servers=brokers)

    class _Writer:
        def write(self, topic: str, value: bytes) -> None:
            producer.send(topic, value).get(timeout=10)

        def close(self) -> None:
            producer.close()

    def _reader_factory(topic: str) -> Reader:
        from kafka import TopicPartition
        from kafka.structs import OffsetAndMetadata

        consumer = KafkaConsumer(
            topic,
            bootstrap_servers=brokers,
            group_id=group,
            auto_offset_reset=offset,
            enable_auto_commit=False,
        )

        class _Reader:
            def read(self, timeout):
                polled = consumer.poll(timeout_ms=int((timeout or 0.5) * 1000),
                                       max_records=1)
                for records in polled.values():
                    for rec in records:
                        # Commit ONLY this record's offset: a bare
                        # consumer.commit() would commit the current
                        # position past earlier uncommitted (failed)
                        # messages, losing them.
                        tp = TopicPartition(rec.topic, rec.partition)
                        meta = OffsetAndMetadata(rec.offset + 1, "")
                        return rec.value, lambda: consumer.commit({tp: meta})
                return None

            def close(self) -> None:
                consumer.close()

        return _Reader()

    class _Admin:
        def __init__(self) -> None:
            self._client = KafkaAdminClient(bootstrap_servers=brokers)

        def create_topic(self, name: str) -> None:
            self._client.create_topics([NewTopic(name, 1, 1)])

        def delete_topic(self, name: str) -> None:
            self._client.delete_topics([name])

        def ping(self) -> bool:
            return bool(self._client.describe_cluster())

        def close(self) -> None:
            self._client.close()

    return KafkaClient(
        _Writer(), _reader_factory, _Admin(), brokers=brokers,
        logger=logger, metrics=metrics,
    )
