"""CLI app (reference: ``pkg/gofr/cmd.go`` + ``pkg/gofr/cmd/``).

``new_cmd()`` builds an app whose routes are regex-matched subcommands over
``sys.argv``; flags become params and bind reflectively into dataclasses
(reference ``cmd.go:27-69``, ``cmd/request.go:25-117``). Output goes to
stdout, errors to stderr (``cmd/responder.go:8-19``). Logs go to
``CMD_LOGS_FILE`` (reference ``gofr.go:99-111``).
"""

from gofr_tpu.cli.cmd import CMDApp, CMDRequest, CMDResponder

__all__ = ["CMDApp", "CMDRequest", "CMDResponder"]
