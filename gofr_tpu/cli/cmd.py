"""CLI transport: regex subcommand routing + flag parsing.

Reference behavior: non-flag args joined into the command string, first
route whose regex matches wins (``cmd.go:32-62``); flags ``-a=b`` / ``--x``
/ ``-bool`` parsed into params (``cmd/request.go:25-67``); ``bind`` maps
params into a dataclass (``cmd/request.go:89-117``); data → stdout, errors →
stderr with exit code (``cmd/responder.go:8-19``).
"""

from __future__ import annotations

import json
import re
import sys
from typing import Any, Callable, Optional

from gofr_tpu.config.env import new_env_file
from gofr_tpu.container import Container
from gofr_tpu.context import Context
from gofr_tpu.logging import new_file_logger


class CMDRequest:
    """Request over argv (reference ``cmd/request.go:14-117``)."""

    def __init__(self, args: list[str]) -> None:
        self._args = args
        self._params: dict[str, str] = {}
        positional: list[str] = []
        for arg in args:
            if arg in ("-", "--", ""):
                continue
            if arg.startswith("-"):
                name = arg.lstrip("-")
                if "=" in name:
                    key, _, value = name.partition("=")
                    self._params[key] = value
                else:
                    self._params[name] = "true"
            else:
                positional.append(arg)
        self.command = " ".join(positional)

    def param(self, key: str) -> str:
        return self._params.get(key, "")

    def path_param(self, key: str) -> str:
        return self.param(key)

    def params(self, key: str) -> list[str]:
        val = self.param(key)
        return val.split(",") if val else []

    @property
    def body(self) -> bytes:
        return b""

    def bind(self, target: Any) -> Any:
        """Reflective param→field bind (reference ``cmd/request.go:89-117``)."""
        from gofr_tpu.http.request import _fill

        return _fill(target, dict(self._params))

    def host_name(self) -> str:
        import socket

        return socket.gethostname()


class CMDResponder:
    """data → stdout, error → stderr (reference ``cmd/responder.go:8-19``)."""

    def __init__(self, out=None, err=None) -> None:
        self._out = out or sys.stdout
        self._err = err or sys.stderr
        self.exit_code = 0

    def respond(self, result: Any, error: Optional[BaseException]) -> None:
        if error is not None:
            self._err.write(f"{error}\n")
            self.exit_code = 1
        if result is not None:
            if isinstance(result, (dict, list)):
                self._out.write(json.dumps(result, default=str) + "\n")
            else:
                self._out.write(f"{result}\n")


class CMDApp:
    """Subcommand app (reference ``cmd.go:27-51`` + ``gofr.go:99-111``)."""

    def __init__(self, config_dir: str = "./configs", config=None) -> None:
        self.config = config if config is not None else new_env_file(config_dir)
        log_file = self.config.get_or_default("CMD_LOGS_FILE", "")
        logger = new_file_logger(log_file)
        self.container = Container.create(self.config, logger=logger)
        self.logger = logger
        self._routes: list[tuple[re.Pattern, Callable, str]] = []

    def sub_command(self, pattern: str, handler: Optional[Callable] = None, description: str = ""):
        """Register a regex-matched subcommand (reference ``cmd.go:65-69``)."""
        if handler is not None:
            self._routes.append((re.compile(pattern), handler, description))
            return handler

        def decorator(fn: Callable):
            self._routes.append((re.compile(pattern), fn, description))
            return fn

        return decorator

    def run(self, argv: Optional[list[str]] = None, out=None, err=None) -> int:
        args = list(sys.argv[1:] if argv is None else argv)
        request = CMDRequest(args)
        responder = CMDResponder(out=out, err=err)

        handler = None
        for pattern, fn, _desc in self._routes:
            if pattern.search(request.command):
                handler = fn
                break
        if handler is None:
            responder.respond(None, Exception("No Command Found!"))
            return responder.exit_code

        ctx = Context(request=request, container=self.container)
        try:
            result = handler(ctx)
            responder.respond(result, None)
        except Exception as exc:
            responder.respond(None, exc)
        return responder.exit_code
