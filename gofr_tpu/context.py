"""Per-request Context (reference ``pkg/gofr/context.go:12-27``).

The facade handlers receive: request access (params, bind), the container's
datasources (``ctx.sql``, ``ctx.redis``, ``ctx.tpu``…), logger, metrics,
custom spans via ``ctx.trace(name)`` (reference ``context.go:45-51``), and
the net-new ``ctx.infer(...)`` primitive that submits work to the dynamic
batcher (SURVEY §2.6 maps it onto ``c.SQL.Select``-style convenience).
"""

from __future__ import annotations

from typing import Any, Optional

from gofr_tpu.tracing import get_tracer


class Context:
    def __init__(self, request, container, responder=None, span=None) -> None:
        self.request = request
        self.container = container
        self._responder = responder
        self._span = span

    # -- request passthrough ----------------------------------------------

    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, target: Any) -> Any:
        return self.request.bind(target)

    def header(self, key: str) -> Optional[str]:
        return self.request.header(key) if hasattr(self.request, "header") else None

    def get(self, key: str, default: Any = None) -> Any:
        """Request-scoped values set by middleware (JWT claims, auth user)."""
        raw = getattr(self.request, "raw", None)
        if raw is not None:
            return raw.ctx_data.get(key, default)
        return default

    # -- request lifecycle (serving/lifecycle.py) -------------------------

    @property
    def deadline(self):
        """The request's Deadline (X-Request-Timeout header or gRPC
        deadline), or None. Handlers pass it to engine submits so
        expired requests retire mid-decode."""
        return self.get("deadline")

    @property
    def cancel_token(self):
        """The request's CancelToken — tripped by the server when the
        client disconnects mid-request. Share it with engine submits so
        abandoned generations free their KV blocks."""
        return self.get("cancel")

    # -- container passthrough --------------------------------------------

    @property
    def logger(self):
        return self.container.logger

    @property
    def metrics(self):
        return self.container.metrics

    @property
    def config(self):
        return self.container.config

    @property
    def sql(self):
        return self.container.sql

    @property
    def redis(self):
        return self.container.redis

    @property
    def pubsub(self):
        return self.container.pubsub

    @property
    def mongo(self):
        return self.container.mongo

    @property
    def tpu(self):
        return self.container.tpu

    def http_service(self, name: str):
        """Registered inter-service client (reference ``container.GetHTTPService``)."""
        return self.container.get_http_service(name)

    def publish(self, topic: str, message: bytes) -> None:
        publisher = self.container.get_publisher()
        if publisher is None:
            raise RuntimeError("no pub/sub backend configured")
        publisher.publish(topic, message)

    # -- tracing (reference context.go:45-51) -----------------------------

    def trace(self, name: str):
        """Open a child span: ``with ctx.trace("work"): ...``"""
        return get_tracer().start_span(name, parent=self._span)

    # -- inference (net-new, SURVEY §2.6) ---------------------------------

    async def infer(self, inputs: Any, model: str = "", **kw) -> Any:
        """Submit inputs to the TPU backend's dynamic batcher and await the
        result. Usable from async handlers; sync handlers use
        ``infer_sync``."""
        if self.container.tpu is None:
            raise RuntimeError("no TPU backend configured (set TPU_MODEL)")
        return await self.container.tpu.infer(inputs, model=model, **kw)

    def infer_sync(self, inputs: Any, model: str = "", **kw) -> Any:
        if self.container.tpu is None:
            raise RuntimeError("no TPU backend configured (set TPU_MODEL)")
        return self.container.tpu.infer_sync(inputs, model=model, **kw)
