"""Span model + W3C traceparent propagation.

Reference behavior being matched: server middleware extracts ``traceparent``
and opens a span per request (``http/middleware/tracer.go:15-32``); handlers
open child spans via ``ctx.Trace(name)`` (``context.go:45-51``); clients
inject ``traceparent`` downstream (``service/new.go:158``).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from dataclasses import dataclass, field
from types import TracebackType
from typing import Any, Optional

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "gofr_tpu_current_span", default=None
)


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    start_ns: int = 0
    end_ns: Optional[int] = None
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "OK"
    _tracer: Optional["Tracer"] = None
    _token: Optional[contextvars.Token[Optional["Span"]]] = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def end(self) -> None:
        if self.end_ns is not None:
            return
        self.end_ns = time.time_ns()
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                _current_span.set(None)
            self._token = None
        if self._tracer is not None:
            self._tracer._on_end(self)

    @property
    def duration_us(self) -> int:
        end = self.end_ns if self.end_ns is not None else time.time_ns()
        return (end - self.start_ns) // 1000

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    # context-manager sugar: `with ctx.trace("name"):`
    def __enter__(self) -> "Span":
        return self

    def __exit__(
        self,
        exc_type: Optional[type[BaseException]],
        exc: Optional[BaseException],
        tb: Optional[TracebackType],
    ) -> None:
        if exc is not None:
            self.set_status("ERROR")
            self.set_attribute("error.message", str(exc))
        self.end()


class Tracer:
    """Creates spans and hands completed ones to an exporter."""

    def __init__(
        self, service_name: str = "gofr-tpu-app", exporter: Any = None
    ) -> None:
        self.service_name = service_name
        self._exporter = exporter
        self._lock = threading.Lock()

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        attributes: Optional[dict[str, Any]] = None,
    ) -> Span:
        if parent is None:
            parent = _current_span.get()
        if parent is not None:
            trace_id = parent.trace_id
            parent_span_id = parent.span_id
        span = Span(
            name=name,
            trace_id=trace_id or _rand_hex(16),
            span_id=_rand_hex(8),
            parent_id=parent_span_id,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
            _tracer=self,
        )
        span._token = _current_span.set(span)
        return span

    def emit_span(
        self,
        name: str,
        *,
        trace_id: str,
        parent_span_id: Optional[str] = None,
        start_ns: int,
        end_ns: int,
        attributes: Optional[dict[str, Any]] = None,
        status: str = "OK",
    ) -> Span:
        """Export an already-completed span with explicit timestamps.

        The serving observability layer (``serving/observability.py``)
        reconstructs a request's phase spans at retirement from host
        timestamps it collected along the way — emitting them live from
        the scheduler's dispatch path would put clock reads and exporter
        queue traffic on the decode hot path. This constructs the span
        fully ended (never touching the ambient context-var, so the
        scheduler thread's context is untouched) and hands it straight
        to the exporter."""
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_rand_hex(8),
            parent_id=parent_span_id,
            start_ns=int(start_ns),
            end_ns=int(end_ns),
            attributes=dict(attributes or {}),
            status=status,
            _tracer=None,  # already ended; do not re-enter _on_end
        )
        self._on_end(span)
        return span

    def _on_end(self, span: Span) -> None:
        if self._exporter is not None:
            self._exporter.export(span, self.service_name)

    def shutdown(self) -> None:
        if self._exporter is not None and hasattr(self._exporter, "shutdown"):
            self._exporter.shutdown()


def current_span() -> Optional[Span]:
    return _current_span.get()


_global_tracer = Tracer()


def get_tracer() -> Tracer:
    return _global_tracer


def set_tracer(tracer: Tracer) -> None:
    global _global_tracer
    _global_tracer = tracer


def extract_traceparent(
    headers: dict[str, str],
) -> tuple[Optional[str], Optional[str]]:
    """Parse W3C ``traceparent`` → (trace_id, parent_span_id)."""
    tp = headers.get("traceparent", "")
    parts = tp.split("-")
    if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
        return parts[1], parts[2]
    return None, None


def inject_traceparent(
    headers: dict[str, str], span: Optional[Span] = None
) -> dict[str, str]:
    span = span or current_span()
    if span is not None:
        headers["traceparent"] = span.traceparent()
    return headers
