"""Trace exporters (reference ``exporter.go:22-130`` + ``gofr.go:250-300``).

Completed spans are queued and shipped by a background daemon thread in
JSON batches. Two wire formats, matching the reference's distinct sinks:

* **Zipkin JSON** — the shape of the reference's custom/zipkin exporters
  (``exporter.go:58-96`` builds ``[{id, traceId, parentId, name,
  timestamp, duration, tags}]``; zipkin at ``gofr.go:282``).
* **OTLP/HTTP JSON** — the reference treats jaeger as its own
  OTLP exporter (``gofr.go:277-286``, OTLP-gRPC); here jaeger maps to
  the standard OTLP/HTTP transport (``/v1/traces``,
  ``ExportTraceServiceRequest`` JSON) that jaeger ≥1.35 ingests natively
  on :4318 — a distinct protocol, not a zipkin alias (VERDICT r2
  missing #2).

Console and noop exporters cover dev/test. Selection mirrors the
reference's env switch (``gofr.go:251-253``): ``TRACE_EXPORTER`` ∈
{zipkin, gofr, jaeger, otlp, console, none} + ``TRACER_URL``.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Any

from gofr_tpu.tracing.tracer import Span


class NoopExporter:
    #: Lets callers (serving/observability.py) skip span construction
    #: entirely when completed spans would go nowhere.
    is_noop = True

    def export(self, span: Span, service_name: str) -> None:  # noqa: ARG002
        pass

    def shutdown(self) -> None:
        pass


class ConsoleExporter:
    def __init__(self, logger: Any = None) -> None:
        self._logger = logger

    def export(self, span: Span, service_name: str) -> None:
        line = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "parentId": span.parent_id,
            "name": span.name,
            "durationUs": span.duration_us,
            "service": service_name,
            "tags": {str(k): str(v) for k, v in span.attributes.items()},
        }
        if self._logger is not None:
            self._logger.debug(line)
        else:
            print(json.dumps(line))


class _BatchingHTTPExporter:
    """Queue + daemon-thread batching over an HTTP POST sink (reference
    ``exporter.go:48-130``). Subclasses define ``_convert`` (span → wire
    dict) and ``_encode`` (batch → request body)."""

    def __init__(
        self,
        url: str,
        logger: Any = None,
        batch_size: int = 64,
        flush_interval_s: float = 2.0,
    ) -> None:
        self._url = url
        self._logger = logger
        self._batch_size = batch_size
        self._interval = flush_interval_s
        self._queue: "queue.Queue[tuple[Span, str]]" = queue.Queue(
            maxsize=4096
        )
        self._stop = threading.Event()
        self._failed_once = False
        self._thread = threading.Thread(target=self._run, name="trace-exporter", daemon=True)
        self._thread.start()

    def export(self, span: Span, service_name: str) -> None:
        try:
            self._queue.put_nowait((span, service_name))
        except queue.Full:
            pass  # drop rather than block the request path

    def _convert(self, span: Span, service_name: str) -> dict:
        raise NotImplementedError

    def _encode(self, batch: list[dict]) -> bytes:
        raise NotImplementedError

    def _run(self) -> None:
        batch: list[dict] = []
        while not self._stop.is_set():
            try:
                span, svc = self._queue.get(timeout=self._interval)
                batch.append(self._convert(span, svc))
            except queue.Empty:
                pass
            if batch and (len(batch) >= self._batch_size or self._queue.empty()):
                self._post(batch)
                batch = []
        while not self._queue.empty():
            span, svc = self._queue.get_nowait()
            batch.append(self._convert(span, svc))
        if batch:
            self._post(batch)

    def _post(self, batch: list[dict]) -> None:
        try:
            req = urllib.request.Request(
                self._url,
                data=self._encode(batch),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as exc:
            if self._logger is not None:
                # First failure at ERROR so a misconfigured sink (wrong
                # protocol/endpoint → every batch dropped) is visible at
                # default log level; repeats stay at debug.
                if not self._failed_once:
                    self._failed_once = True
                    self._logger.errorf(
                        "trace export to %s failed (further failures "
                        "logged at debug): %s", self._url, exc,
                    )
                else:
                    self._logger.debugf("trace export failed: %s", exc)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class ZipkinExporter(_BatchingHTTPExporter):
    """Zipkin-JSON HTTP exporter (reference ``exporter.go:58-96`` shape;
    also serves the hosted "gofr" sink, ``exporter.go:22-33``)."""

    def _convert(self, span: Span, service_name: str) -> dict:
        out: dict[str, Any] = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "name": span.name,
            "timestamp": span.start_ns // 1000,
            "duration": span.duration_us,
            "localEndpoint": {"serviceName": service_name},
            "tags": {str(k): str(v) for k, v in span.attributes.items()},
        }
        if span.parent_id:
            out["parentId"] = span.parent_id
        return out

    def _encode(self, batch: list[dict]) -> bytes:
        return json.dumps(batch).encode()


class OTLPExporter(_BatchingHTTPExporter):
    """OTLP/HTTP JSON trace exporter (the reference's jaeger sink is OTLP,
    ``gofr.go:277-286``; jaeger ingests OTLP/HTTP natively on :4318
    ``/v1/traces``). Emits ``ExportTraceServiceRequest`` JSON:
    resourceSpans → scopeSpans → spans, with OTel AnyValue attributes."""

    _STATUS_CODES = {"OK": 1, "ERROR": 2}

    def _convert(self, span: Span, service_name: str) -> dict:
        # Exact end timestamp when the span was properly ended; derive
        # from duration only as a fallback.
        end_ns = span.end_ns or (span.start_ns + span.duration_us * 1000)
        out: dict[str, Any] = {
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "name": span.name,
            # Root spans are server entry points; child spans (ctx.trace)
            # are INTERNAL — span-kind-based processors (spanmetrics,
            # service graphs) count SERVER spans as requests.
            "kind": 1 if span.parent_id else 2,
            "startTimeUnixNano": str(span.start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [
                {"key": str(k), "value": {"stringValue": str(v)}}
                for k, v in span.attributes.items()
            ],
            "status": {
                "code": self._STATUS_CODES.get(
                    getattr(span, "status", "OK"), 0
                )
            },
            "_service": service_name,  # grouped by _encode, then dropped
        }
        if span.parent_id:
            out["parentSpanId"] = span.parent_id
        return out

    def _encode(self, batch: list[dict]) -> bytes:
        by_service: dict[str, list[dict]] = {}
        for span in batch:
            svc = span.pop("_service", "unknown")
            by_service.setdefault(svc, []).append(span)
        return json.dumps({
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [{
                            "key": "service.name",
                            "value": {"stringValue": svc},
                        }],
                    },
                    "scopeSpans": [{
                        "scope": {"name": "gofr-tpu"},
                        "spans": spans,
                    }],
                }
                for svc, spans in by_service.items()
            ],
        }).encode()


def exporter_from_config(config: Any, logger: Any = None) -> Any:
    """Reference ``gofr.go:250-300``: TRACE_EXPORTER + TRACER_URL select the
    sink — zipkin/gofr speak Zipkin JSON, jaeger/otlp speak OTLP/HTTP."""
    name = (config.get_or_default("TRACE_EXPORTER", "") or "").lower()
    url = config.get_or_default("TRACER_URL", "")
    if name in ("jaeger", "otlp") and url:
        # OTLP/HTTP's trace path is fixed; default it so TRACER_URL can be
        # just the collector base (e.g. http://jaeger:4318).
        from urllib.parse import urlparse

        if urlparse(url).path in ("", "/"):
            url = url.rstrip("/") + "/v1/traces"
        return OTLPExporter(url, logger=logger)
    if name in ("zipkin", "gofr") and url:
        return ZipkinExporter(url, logger=logger)
    if name == "console":
        return ConsoleExporter(logger=logger)
    return NoopExporter()
