"""Trace exporters (reference ``exporter.go:22-130`` + ``gofr.go:250-300``).

Completed spans are queued and shipped by a background daemon thread in
Zipkin-style JSON batches — the exact shape of the reference's custom
exporter (``exporter.go:58-96`` builds ``[{id, traceId, parentId, name,
timestamp, duration, tags}]``). Console and noop exporters cover dev/test.

Selection mirrors the reference's env switch (``gofr.go:251-253``):
``TRACE_EXPORTER`` ∈ {zipkin, console, none} + ``TRACER_URL``.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request


class NoopExporter:
    def export(self, span, service_name: str) -> None:  # noqa: ARG002
        pass

    def shutdown(self) -> None:
        pass


class ConsoleExporter:
    def __init__(self, logger=None) -> None:
        self._logger = logger

    def export(self, span, service_name: str) -> None:
        line = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "parentId": span.parent_id,
            "name": span.name,
            "durationUs": span.duration_us,
            "service": service_name,
            "tags": {str(k): str(v) for k, v in span.attributes.items()},
        }
        if self._logger is not None:
            self._logger.debug(line)
        else:
            print(json.dumps(line))


class ZipkinExporter:
    """Batching Zipkin-JSON HTTP exporter (reference ``exporter.go:48-130``)."""

    def __init__(self, url: str, logger=None, batch_size: int = 64, flush_interval_s: float = 2.0) -> None:
        self._url = url
        self._logger = logger
        self._batch_size = batch_size
        self._interval = flush_interval_s
        self._queue: queue.Queue = queue.Queue(maxsize=4096)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="trace-exporter", daemon=True)
        self._thread.start()

    def export(self, span, service_name: str) -> None:
        try:
            self._queue.put_nowait((span, service_name))
        except queue.Full:
            pass  # drop rather than block the request path

    def _convert(self, span, service_name: str) -> dict:
        # Zipkin span JSON (reference exporter.go:58-96).
        out = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "name": span.name,
            "timestamp": span.start_ns // 1000,
            "duration": span.duration_us,
            "localEndpoint": {"serviceName": service_name},
            "tags": {str(k): str(v) for k, v in span.attributes.items()},
        }
        if span.parent_id:
            out["parentId"] = span.parent_id
        return out

    def _run(self) -> None:
        batch: list[dict] = []
        while not self._stop.is_set():
            try:
                span, svc = self._queue.get(timeout=self._interval)
                batch.append(self._convert(span, svc))
            except queue.Empty:
                pass
            if batch and (len(batch) >= self._batch_size or self._queue.empty()):
                self._post(batch)
                batch = []
        while not self._queue.empty():
            span, svc = self._queue.get_nowait()
            batch.append(self._convert(span, svc))
        if batch:
            self._post(batch)

    def _post(self, batch: list[dict]) -> None:
        try:
            req = urllib.request.Request(
                self._url,
                data=json.dumps(batch).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            urllib.request.urlopen(req, timeout=5).read()
        except Exception as exc:
            if self._logger is not None:
                self._logger.debugf("trace export failed: %s", exc)

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def exporter_from_config(config, logger=None):
    """Reference ``gofr.go:250-300``: TRACE_EXPORTER + TRACER_URL select the sink."""
    name = (config.get_or_default("TRACE_EXPORTER", "") or "").lower()
    url = config.get_or_default("TRACER_URL", "")
    if name in ("zipkin", "gofr", "jaeger") and url:
        return ZipkinExporter(url, logger=logger)
    if name == "console":
        return ConsoleExporter(logger=logger)
    return NoopExporter()
