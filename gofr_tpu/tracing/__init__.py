"""Distributed tracing (reference: OTel wiring in ``gofr.go:250-300`` +
``http/middleware/tracer.go`` + ``exporter.go``).

A lightweight native tracer: W3C ``traceparent`` propagation, contextvar-scoped
spans, and pluggable batch exporters (console, Zipkin-JSON over HTTP — the
shape of the reference's custom exporter, ``exporter.go:58-130``).
"""

from gofr_tpu.tracing.tracer import (
    Span,
    Tracer,
    current_span,
    extract_traceparent,
    get_tracer,
    inject_traceparent,
    set_tracer,
)
from gofr_tpu.tracing.exporter import (
    ConsoleExporter,
    NoopExporter,
    OTLPExporter,
    ZipkinExporter,
    exporter_from_config,
)

__all__ = [
    "Span",
    "Tracer",
    "current_span",
    "get_tracer",
    "set_tracer",
    "extract_traceparent",
    "inject_traceparent",
    "ConsoleExporter",
    "NoopExporter",
    "OTLPExporter",
    "ZipkinExporter",
    "exporter_from_config",
]
