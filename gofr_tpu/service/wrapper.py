"""Shared decorator shape for service client options (reference
``service/options.go:3-5`` — options fold wrappers over the base client).

Every option wrapper delegates unknown attributes to the wrapped service
and routes the five verb helpers through its own ``request`` so a single
override point intercepts all traffic.
"""

from __future__ import annotations

from typing import Any


def innermost(svc: Any) -> Any:
    """Walk the ``_inner`` chain to the base HTTPService."""
    while hasattr(svc, "_inner"):
        svc = svc._inner
    return svc


class ServiceWrapper:
    """Decorator base: wraps a service, delegates everything else."""

    def __init__(self, inner: Any) -> None:
        self._inner = inner

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def request(self, method: str, path: str, **kw: Any) -> Any:
        return self._inner.request(method, path, **kw)

    def get(self, path: str, params: Any = None, headers: Any = None) -> Any:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Any:
        return self.request("POST", path, params=params, body=body, json=json, headers=headers)

    def put(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Any:
        return self.request("PUT", path, params=params, body=body, json=json, headers=headers)

    def patch(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Any:
        return self.request("PATCH", path, params=params, body=body, json=json, headers=headers)

    def delete(self, path: str, params: Any = None, body: Any = None, headers: Any = None) -> Any:
        return self.request("DELETE", path, params=params, body=body, headers=headers)
