"""Shared decorator shape for service client options (reference
``service/options.go:3-5`` — options fold wrappers over the base client).

Every option wrapper delegates unknown attributes to the wrapped service
and routes the five verb helpers through its own ``request`` so a single
override point intercepts all traffic.
"""

from __future__ import annotations


def innermost(svc):
    """Walk the ``_inner`` chain to the base HTTPService."""
    while hasattr(svc, "_inner"):
        svc = svc._inner
    return svc


class ServiceWrapper:
    """Decorator base: wraps a service, delegates everything else."""

    def __init__(self, inner) -> None:
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def request(self, method: str, path: str, **kw):
        return self._inner.request(method, path, **kw)

    def get(self, path, params=None, headers=None):
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path, params=None, body=None, json=None, headers=None):
        return self.request("POST", path, params=params, body=body, json=json, headers=headers)

    def put(self, path, params=None, body=None, json=None, headers=None):
        return self.request("PUT", path, params=params, body=body, json=json, headers=headers)

    def patch(self, path, params=None, body=None, json=None, headers=None):
        return self.request("PATCH", path, params=params, body=body, json=json, headers=headers)

    def delete(self, path, params=None, body=None, headers=None):
        return self.request("DELETE", path, params=params, body=body, headers=headers)
