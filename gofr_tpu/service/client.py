"""Base HTTP service client.

Reference behavior (``service/new.go:26-211``): per-call span with
traceparent injection, ``app_http_service_response`` histogram, structured
request logs, ``Response{body, status_code}`` + header access, and a
``.well-known/alive`` health probe consumed by the container's aggregate
health (``container/health.go:23-25``).

Network-failure semantics the replica tier builds on:

* **Separate connect and read budgets** — ``connect_timeout_s`` bounds
  only the TCP/TLS handshake while ``timeout`` bounds the response read.
  A *connect* failure means nothing is listening (dead upstream); a
  *read* timeout usually means a live upstream busy behind queued work.
  Conflating the two made the replica prober demote loaded-but-alive
  remotes; every transport error raised here carries a ``kind``
  attribute (``"connect"`` / ``"read"`` / ``"transport"``) so callers
  can tell them apart.
* **Deterministic fault points** — ``faults.fire("http.request")`` in
  :meth:`HTTPService.request` and ``http.stream.open`` /
  ``http.stream.event`` in :meth:`HTTPService.stream_lines` let the
  network-chaos suite inject connect-refused, 5xx bursts, mid-body
  resets, truncated SSE streams and read-stalls without real sockets
  (see ``gofr_tpu/faults`` and ``tests/test_remote_failover.py``).
"""

from __future__ import annotations

import json as jsonlib
import time
from contextlib import contextmanager
from typing import Any, Iterator, Mapping, Optional

import httpx

from gofr_tpu import faults
from gofr_tpu.tracing import (
    current_span,
    extract_traceparent,
    get_tracer,
    inject_traceparent,
)


def _client_span(name: str, hdrs: Mapping[str, str], url: str) -> Any:
    """Client span for an outbound request. The ambient contextvar span
    parents it when one exists (the in-app handler case). Without one —
    the replica tier submits from detached worker threads, where the
    contextvar chain is broken but the routing tier's trace context
    rides the request as an explicit ``traceparent`` header — the span
    joins THAT trace, so the header re-injected downstream carries the
    same trace id with this span as parent: one trace across hosts."""
    tracer = get_tracer()
    if current_span() is None:
        trace_id, parent_id = extract_traceparent(hdrs)
        if trace_id:
            return tracer.start_span(
                name, trace_id=trace_id, parent_span_id=parent_id,
                attributes={"http.url": url},
            )
    return tracer.start_span(name, attributes={"http.url": url})


def classify_transport_error(exc: BaseException) -> str:
    """Map a transport failure to its ``kind``: ``"connect"`` (nothing
    accepted the connection — the upstream is gone), ``"read"`` (the
    connection lives but bytes stopped — busy or stalled upstream), or
    ``"transport"`` (anything else on the wire)."""
    if isinstance(exc, (httpx.ConnectError, httpx.ConnectTimeout)):
        return "connect"
    if isinstance(exc, (httpx.ReadTimeout, httpx.ReadError)):
        return "read"
    kind = getattr(exc, "kind", None)
    return kind if isinstance(kind, str) else "transport"


def _unavailable(address: str, exc: BaseException) -> Exception:
    """Typed 503 for a transport failure, tagged with the failure kind
    so the replica tier can classify dead-vs-busy correctly."""
    from gofr_tpu.errors import ErrorServiceUnavailable

    err = ErrorServiceUnavailable(f"{address}: {exc}")
    err.kind = classify_transport_error(exc)  # type: ignore[attr-defined]
    return err


class Response:
    def __init__(self, body: bytes, status_code: int, headers: Mapping[str, str]) -> None:
        self.body = body
        self.status_code = status_code
        self._headers = dict(headers)

    def get_header(self, key: str) -> str:
        return self._headers.get(key, self._headers.get(key.lower(), ""))

    def json(self) -> Any:
        return jsonlib.loads(self.body or b"null")


class ServiceLog:
    """Structured outbound-call log (reference ``service/logger.go:13-37``)."""

    def __init__(self, method: str, url: str, status: int, duration_us: int, trace_id: str) -> None:
        self.method = method
        self.url = url
        self.status = status
        self.duration = duration_us
        self.trace_id = trace_id

    def to_log_dict(self) -> dict:
        return {
            "method": self.method, "uri": self.url, "response_code": self.status,
            "response_time": self.duration, "trace_id": self.trace_id,
        }

    def pretty_print(self, fp: Any) -> None:
        fp.write(
            f"\x1b[38;5;8mSVC\x1b[0m {self.duration:>8}µs {self.status} "
            f"{self.method} {self.url}\n"
        )


class HTTPService:
    """Concrete client; options wrap/extend it (``AddOption`` pattern)."""

    def __init__(
        self,
        address: str,
        logger: Any = None,
        metrics: Any = None,
        timeout: float = 30.0,
        connect_timeout_s: Optional[float] = None,
    ) -> None:
        self.address = address.rstrip("/")
        self._logger = logger
        self._metrics = metrics
        self.timeout = float(timeout)
        # Connect budget separate from (and much shorter than) the read
        # budget: a dead upstream refuses/blackholes the HANDSHAKE in
        # ~RTT time, while a busy-but-alive one accepts instantly and
        # is merely slow to ANSWER. One shared budget forced callers to
        # wait the full read timeout to learn nothing is listening — or,
        # worse, to classify a loaded replica as dead.
        self.connect_timeout_s = float(
            connect_timeout_s
            if connect_timeout_s is not None
            else min(self.timeout, 5.0)
        )
        self._client = httpx.Client(
            timeout=httpx.Timeout(
                self.timeout, connect=self.connect_timeout_s
            )
        )
        self.health_endpoint = ".well-known/alive"  # reference service/health.go:18-20

    # -- core request (reference service/new.go:135-192) ------------------

    def request(
        self,
        method: str,
        path: str,
        *,
        params: Optional[Mapping[str, Any]] = None,
        headers: Optional[Mapping[str, str]] = None,
        body: Optional[bytes] = None,
        json: Any = None,
    ) -> Response:
        url = f"{self.address}/{path.lstrip('/')}" if path else self.address
        hdrs = dict(headers or {})
        span = _client_span(f"http-service {method} {url}", hdrs, url)
        inject_traceparent(hdrs, span)
        start = time.time()
        status = 0
        try:
            # Chaos seam: an armed fault either raises a transport error
            # (connect-refused) or returns a canned Response (5xx burst)
            # — the full request path below it stays exercised.
            canned = faults.fire(
                "http.request", address=self.address, method=method,
                path=path,
            )
            if isinstance(canned, Response):
                status = canned.status_code
                return canned
            try:
                resp = self._client.request(
                    method, url, params=params, headers=hdrs, content=body, json=json
                )
            except httpx.TransportError as exc:
                # Downstream unreachable → typed 503, not an anonymous 500
                # (the responder honors status_code; the breaker still counts
                # the raised error as a failure). The `kind` tag keeps
                # connect-vs-read distinguishable for the replica prober.
                raise _unavailable(self.address, exc) from exc
            status = resp.status_code
            return Response(resp.content, resp.status_code, resp.headers)
        finally:
            elapsed = time.time() - start
            span.set_attribute("http.status_code", status)
            span.end()
            if self._metrics is not None:
                self._metrics.record_histogram(
                    "app_http_service_response", elapsed,
                    "path", f"{self.address}/{path.lstrip('/')}", "method", method,
                    "status", str(status),
                )
            log = ServiceLog(method, url, status, int(elapsed * 1e6), span.trace_id)
            if self._logger is not None:
                if status == 0 or status >= 500:
                    self._logger.error(log)
                else:
                    self._logger.debug(log)

    # -- verb helpers (reference service/new.go:89-133) --------------------

    def get(self, path: str, params: Any = None, headers: Any = None) -> Response:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("POST", path, params=params, body=body, json=json, headers=headers)

    def put(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("PUT", path, params=params, body=body, json=json, headers=headers)

    def patch(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("PATCH", path, params=params, body=body, json=json, headers=headers)

    def delete(self, path: str, params: Any = None, body: Any = None, headers: Any = None) -> Response:
        return self.request("DELETE", path, params=params, body=body, headers=headers)

    # -- streaming (SSE consumer for remote replicas) -----------------------

    @contextmanager
    def stream_lines(
        self,
        method: str,
        path: str,
        *,
        json: Any = None,
        headers: Optional[Mapping[str, str]] = None,
        read_timeout_s: Optional[float] = None,
    ) -> Iterator[Iterator[str]]:
        """Open a streaming request and yield an iterator of decoded
        response LINES (the SSE framing unit). ``read_timeout_s`` is the
        per-read idle budget: an upstream that stops sending bytes for
        longer raises a ``kind="read"`` 503 mid-iteration — the replica
        tier's stall/slow-loris detector. Connect failures raise a
        ``kind="connect"`` 503 before any line is yielded; non-2xx
        statuses raise with the upstream's status attached.

        Fault points: ``http.stream.open`` fires before the connection
        attempt (raise = connect-refused; return an iterable = serve the
        stream from it, no socket at all); ``http.stream.event`` fires
        per line (raise = mid-body reset; return ``"truncate"`` = EOF
        now, the truncated-SSE fault).
        """
        url = f"{self.address}/{path.lstrip('/')}" if path else self.address
        hdrs = dict(headers or {})
        span = _client_span(
            f"http-service {method} {url} (stream)", hdrs, url
        )
        inject_traceparent(hdrs, span)
        status = 0
        try:
            canned = faults.fire(
                "http.stream.open", address=self.address, method=method,
                path=path,
            )
            if canned is not None:
                status = 200
                yield self._guarded_lines(iter(canned))
                return
            timeout = httpx.Timeout(
                self.timeout if read_timeout_s is None else read_timeout_s,
                connect=self.connect_timeout_s,
            )
            try:
                with self._client.stream(
                    method, url, json=json, headers=hdrs, timeout=timeout
                ) as resp:
                    status = resp.status_code
                    if status >= 400:
                        # Read the (bounded) error body so callers can
                        # map the upstream's status faithfully.
                        body = resp.read()[:2048]
                        from gofr_tpu.errors import GofrError

                        exc = GofrError(
                            f"{self.address} answered {status}: "
                            f"{body.decode(errors='replace')}"
                        )
                        exc.status_code = status
                        raise exc
                    yield self._guarded_lines(resp.iter_lines())
            except httpx.TransportError as exc:
                raise _unavailable(self.address, exc) from exc
        finally:
            span.set_attribute("http.status_code", status)
            span.end()

    def _guarded_lines(self, lines: Iterator[str]) -> Iterator[str]:
        """Wrap a line iterator with the per-event fault point and
        transport-error tagging (mid-body failures surface as tagged
        503s, same contract as the open path)."""
        index = 0
        while True:
            try:
                line = next(lines)
            except StopIteration:
                return
            except httpx.TransportError as exc:
                raise _unavailable(self.address, exc) from exc
            verdict = faults.fire(
                "http.stream.event", address=self.address, index=index,
                line=line,
            )
            if verdict == "truncate":
                return  # upstream vanished mid-stream, no EOF framing
            index += 1
            yield line

    # -- health (reference service/health.go) ------------------------------

    def health_check(self) -> dict:
        try:
            resp = self.get(self.health_endpoint)
            if resp.status_code < 400:
                details: dict[str, Any] = {"host": self.address}
                try:
                    # Surface the upstream's own health payload (engine
                    # state, loaded LoRA adapters, ...) so the replica
                    # tier can read advertised capability sets from one
                    # probe; liveness endpoints with non-JSON bodies
                    # keep the plain host detail.
                    body = resp.json()
                    if isinstance(body, dict):
                        if isinstance(body.get("data"), dict):
                            body = body["data"]  # gofr envelope
                        if isinstance(body.get("details"), dict):
                            details.update(body["details"])
                        if body.get("status"):
                            details["upstream_status"] = body["status"]
                except Exception:  # noqa: BLE001 — liveness bodies may be anything
                    pass
                return {"status": "UP", "details": details}
            return {
                "status": "DOWN",
                "details": {"host": self.address, "error": f"status {resp.status_code}"},
            }
        except Exception as exc:
            details = {"host": self.address, "error": str(exc)}
            kind = getattr(exc, "kind", "")
            if kind:
                details["error_kind"] = kind
            return {"status": "DOWN", "details": details}

    def close(self) -> None:
        self._client.close()


def new_http_service(
    address: str, logger: Any = None, metrics: Any = None, *options: Any
) -> HTTPService:
    """Factory folding option decorators (reference ``service/new.go:68-87``)."""
    svc = HTTPService(address, logger=logger, metrics=metrics)
    for option in options:
        svc = option.add_option(svc)
    return svc
