"""Base HTTP service client.

Reference behavior (``service/new.go:26-211``): per-call span with
traceparent injection, ``app_http_service_response`` histogram, structured
request logs, ``Response{body, status_code}`` + header access, and a
``.well-known/alive`` health probe consumed by the container's aggregate
health (``container/health.go:23-25``).
"""

from __future__ import annotations

import json as jsonlib
import time
from typing import Any, Mapping, Optional

import httpx

from gofr_tpu.tracing import get_tracer, inject_traceparent


class Response:
    def __init__(self, body: bytes, status_code: int, headers: Mapping[str, str]) -> None:
        self.body = body
        self.status_code = status_code
        self._headers = dict(headers)

    def get_header(self, key: str) -> str:
        return self._headers.get(key, self._headers.get(key.lower(), ""))

    def json(self) -> Any:
        return jsonlib.loads(self.body or b"null")


class ServiceLog:
    """Structured outbound-call log (reference ``service/logger.go:13-37``)."""

    def __init__(self, method: str, url: str, status: int, duration_us: int, trace_id: str) -> None:
        self.method = method
        self.url = url
        self.status = status
        self.duration = duration_us
        self.trace_id = trace_id

    def to_log_dict(self) -> dict:
        return {
            "method": self.method, "uri": self.url, "response_code": self.status,
            "response_time": self.duration, "trace_id": self.trace_id,
        }

    def pretty_print(self, fp: Any) -> None:
        fp.write(
            f"\x1b[38;5;8mSVC\x1b[0m {self.duration:>8}µs {self.status} "
            f"{self.method} {self.url}\n"
        )


class HTTPService:
    """Concrete client; options wrap/extend it (``AddOption`` pattern)."""

    def __init__(self, address: str, logger: Any = None, metrics: Any = None, timeout: float = 30.0) -> None:
        self.address = address.rstrip("/")
        self._logger = logger
        self._metrics = metrics
        self._client = httpx.Client(timeout=timeout)
        self.health_endpoint = ".well-known/alive"  # reference service/health.go:18-20

    # -- core request (reference service/new.go:135-192) ------------------

    def request(
        self,
        method: str,
        path: str,
        *,
        params: Optional[Mapping[str, Any]] = None,
        headers: Optional[Mapping[str, str]] = None,
        body: Optional[bytes] = None,
        json: Any = None,
    ) -> Response:
        url = f"{self.address}/{path.lstrip('/')}" if path else self.address
        hdrs = dict(headers or {})
        span = get_tracer().start_span(
            f"http-service {method} {url}", attributes={"http.url": url}
        )
        inject_traceparent(hdrs, span)
        start = time.time()
        status = 0
        try:
            try:
                resp = self._client.request(
                    method, url, params=params, headers=hdrs, content=body, json=json
                )
            except httpx.TransportError as exc:
                # Downstream unreachable → typed 503, not an anonymous 500
                # (the responder honors status_code; the breaker still counts
                # the raised error as a failure).
                from gofr_tpu.errors import ErrorServiceUnavailable

                raise ErrorServiceUnavailable(f"{self.address}: {exc}") from exc
            status = resp.status_code
            return Response(resp.content, resp.status_code, resp.headers)
        finally:
            elapsed = time.time() - start
            span.set_attribute("http.status_code", status)
            span.end()
            if self._metrics is not None:
                self._metrics.record_histogram(
                    "app_http_service_response", elapsed,
                    "path", f"{self.address}/{path.lstrip('/')}", "method", method,
                    "status", str(status),
                )
            log = ServiceLog(method, url, status, int(elapsed * 1e6), span.trace_id)
            if self._logger is not None:
                if status == 0 or status >= 500:
                    self._logger.error(log)
                else:
                    self._logger.debug(log)

    # -- verb helpers (reference service/new.go:89-133) --------------------

    def get(self, path: str, params: Any = None, headers: Any = None) -> Response:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("POST", path, params=params, body=body, json=json, headers=headers)

    def put(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("PUT", path, params=params, body=body, json=json, headers=headers)

    def patch(self, path: str, params: Any = None, body: Any = None, json: Any = None, headers: Any = None) -> Response:
        return self.request("PATCH", path, params=params, body=body, json=json, headers=headers)

    def delete(self, path: str, params: Any = None, body: Any = None, headers: Any = None) -> Response:
        return self.request("DELETE", path, params=params, body=body, headers=headers)

    # -- health (reference service/health.go) ------------------------------

    def health_check(self) -> dict:
        try:
            resp = self.get(self.health_endpoint)
            if resp.status_code < 400:
                return {"status": "UP", "details": {"host": self.address}}
            return {
                "status": "DOWN",
                "details": {"host": self.address, "error": f"status {resp.status_code}"},
            }
        except Exception as exc:
            return {"status": "DOWN", "details": {"host": self.address, "error": str(exc)}}

    def close(self) -> None:
        self._client.close()


def new_http_service(
    address: str, logger: Any = None, metrics: Any = None, *options: Any
) -> HTTPService:
    """Factory folding option decorators (reference ``service/new.go:68-87``)."""
    svc = HTTPService(address, logger=logger, metrics=metrics)
    for option in options:
        svc = option.add_option(svc)
    return svc
