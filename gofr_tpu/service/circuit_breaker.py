"""Circuit breaker option (reference ``service/circuit_breaker.go:12-212``).

Closed → Open after ``threshold`` consecutive failures; while Open, calls
fast-fail with :class:`CircuitOpenError` and a background ticker probes the
health endpoint every ``interval`` seconds to auto-close (reference
``circuit_breaker.go:57-96,106-118``); a request-path probe also closes the
circuit when a live call succeeds after recovery.

The probe ticker is a daemon thread that is **stopped by ``close()``** —
a breaker must not keep probing a service whose client was torn down —
and breaker state is surfaced as the ``app_http_service_circuit_open``
gauge (1 = open) labeled by service address, so dashboards see an open
circuit the moment it opens rather than inferring it from error rates.
"""

from __future__ import annotations

import threading

import time
from dataclasses import dataclass
from typing import Any

from gofr_tpu.analysis import lockcheck
from gofr_tpu.service.wrapper import ServiceWrapper, innermost


class CircuitOpenError(Exception):
    def __init__(self) -> None:
        super().__init__("circuit breaker is open; service unavailable")
        self.status_code = 503


@dataclass
class CircuitBreakerConfig:
    threshold: int = 5
    interval_s: float = 10.0

    def add_option(self, svc: Any) -> "_CircuitBreakerService":
        return _CircuitBreakerService(svc, self.threshold, self.interval_s)


class _CircuitBreakerService(ServiceWrapper):
    """Wraps an HTTPService; delegates everything else."""

    def __init__(self, inner: Any, threshold: int, interval_s: float) -> None:
        super().__init__(inner)
        self._threshold = threshold
        self._interval = interval_s
        self._lock = lockcheck.make_lock("_CircuitBreakerService._lock")
        self._failures = 0
        self._open = False
        self._opened_at = 0.0
        self._closed = False  # client torn down; no more tickers
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def _publish_state(self, open_: bool) -> None:
        """Breaker state gauge, labeled by the wrapped service address."""
        base = innermost(self)
        metrics = getattr(base, "_metrics", None)
        if metrics is not None:
            metrics.set_gauge(
                "app_http_service_circuit_open",
                1.0 if open_ else 0.0,
                "service", getattr(base, "address", "unknown"),
            )

    def _record_success(self) -> None:
        with self._lock:
            self._failures = 0
            was_open, self._open = self._open, False
        if was_open:
            self._publish_state(False)
        self._stop_ticker()

    def note_probe_success(self) -> None:
        """An out-of-band synthetic probe (replica pool's active prober)
        succeeded against the wrapped service: it demonstrably serves
        REAL traffic again, which is stronger evidence than the health
        ticker's liveness poll. Half-open the breaker NOW — reset the
        failure count and let requests flow — instead of making callers
        wait out the remainder of the probe interval on a replica that
        already returned to SERVING. No-op on a closed breaker."""
        self._record_success()

    def _record_failure(self) -> None:
        start_ticker = False
        with self._lock:
            self._failures += 1
            if self._failures >= self._threshold and not self._open:
                self._open = True
                self._opened_at = time.time()
                start_ticker = not self._closed
        if start_ticker:
            self._publish_state(True)
            self._start_ticker()

    def _start_ticker(self) -> None:
        """Health-probe loop to auto-close (reference ``:106-118``).
        Daemon: it must never pin the interpreter open, and ``close()``
        stops it explicitly so it cannot outlive the client either.
        The ``_closed`` re-check and the stop-clear both hold the lock:
        a failure racing ``close()`` could otherwise observe
        ``_closed=False``, lose the lock, and then spawn a ticker whose
        ``_stop.clear()`` undoes close()'s stop signal — resurrecting
        exactly the leak close() exists to prevent."""
        with self._lock:
            if self._closed:
                return
            self._stop.clear()
            self._ticker = threading.Thread(
                target=self._probe_loop, name="circuit-breaker-probe",
                daemon=True,
            )
            self._ticker.start()

    def _stop_ticker(self) -> None:
        self._stop.set()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._interval):
            if self._healthy():
                self._record_success()
                return

    def _healthy(self) -> bool:
        try:
            return self._inner.health_check().get("status") == "UP"
        except Exception:
            return False

    def close(self) -> None:
        """Stop the probe ticker with the client (the ticker previously
        could outlive it, probing a dead address forever), then close
        the wrapped service."""
        with self._lock:
            self._closed = True
        self._stop.set()
        ticker = self._ticker
        if ticker is not None and ticker.is_alive():
            ticker.join(timeout=5)
        self._ticker = None
        inner_close = getattr(self._inner, "close", None)
        if callable(inner_close):
            inner_close()

    def request(self, method: str, path: str, **kw: Any) -> Any:
        if self.is_open:
            # Recovery probe on the request path (reference :149-156).
            if self._healthy():
                self._record_success()
            else:
                raise CircuitOpenError()
        try:
            resp = self._inner.request(method, path, **kw)
        except Exception:
            self._record_failure()
            raise
        if resp.status_code >= 500:
            self._record_failure()
        else:
            self._record_success()
        return resp
