"""Circuit breaker option (reference ``service/circuit_breaker.go:12-212``).

Closed → Open after ``threshold`` consecutive failures; while Open, calls
fast-fail with :class:`CircuitOpenError` and a background ticker probes the
health endpoint every ``interval`` seconds to auto-close (reference
``circuit_breaker.go:57-96,106-118``); a request-path probe also closes the
circuit when a live call succeeds after recovery.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from gofr_tpu.service.wrapper import ServiceWrapper


class CircuitOpenError(Exception):
    def __init__(self) -> None:
        super().__init__("circuit breaker is open; service unavailable")
        self.status_code = 503


@dataclass
class CircuitBreakerConfig:
    threshold: int = 5
    interval_s: float = 10.0

    def add_option(self, svc):
        return _CircuitBreakerService(svc, self.threshold, self.interval_s)


class _CircuitBreakerService(ServiceWrapper):
    """Wraps an HTTPService; delegates everything else."""

    def __init__(self, inner, threshold: int, interval_s: float) -> None:
        super().__init__(inner)
        self._threshold = threshold
        self._interval = interval_s
        self._lock = threading.Lock()
        self._failures = 0
        self._open = False
        self._opened_at = 0.0
        self._stop = threading.Event()
        self._ticker: threading.Thread | None = None

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def _record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._open:
                self._open = False
        self._stop_ticker()

    def _record_failure(self) -> None:
        start_ticker = False
        with self._lock:
            self._failures += 1
            if self._failures >= self._threshold and not self._open:
                self._open = True
                self._opened_at = time.time()
                start_ticker = True
        if start_ticker:
            self._start_ticker()

    def _start_ticker(self) -> None:
        """Health-probe loop to auto-close (reference ``:106-118``)."""
        self._stop.clear()
        self._ticker = threading.Thread(
            target=self._probe_loop, name="circuit-breaker-probe", daemon=True
        )
        self._ticker.start()

    def _stop_ticker(self) -> None:
        self._stop.set()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._interval):
            if self._healthy():
                self._record_success()
                return

    def _healthy(self) -> bool:
        try:
            return self._inner.health_check().get("status") == "UP"
        except Exception:
            return False

    def request(self, method: str, path: str, **kw):
        if self.is_open:
            # Recovery probe on the request path (reference :149-156).
            if self._healthy():
                self._record_success()
            else:
                raise CircuitOpenError()
        try:
            resp = self._inner.request(method, path, **kw)
        except Exception:
            self._record_failure()
            raise
        if resp.status_code >= 500:
            self._record_failure()
        else:
            self._record_success()
        return resp
