"""Replica-tier failover: health-aware routing around DOWN engines.

PR 3 made a SINGLE engine self-healing — but a replica that exhausts
``TPU_RESTART_MAX`` still lands DOWN and takes its traffic with it. This
module is the layer above: a :class:`ReplicaPool` fronts N inference
backends (in-process :class:`~gofr_tpu.serving.engine.InferenceEngine`
replicas and/or remote ``HTTPService`` endpoints) and makes the POOL the
availability boundary, the way vLLM/Pathways-style deployments treat
the router rather than the engine as the unit that must never die.

What the pool owns:

* **Health-aware routing** — every submit picks the least-loaded
  replica among SERVING ones (round-robin tie-break so equal-load
  replicas share traffic), spills to DEGRADED when nothing is SERVING,
  and never routes to RESTARTING/DOWN or probe-demoted replicas. With
  no routable replica at all, submits fail fast with
  :class:`~gofr_tpu.errors.ErrorNoHealthyReplica` (502 — the routing
  tier found no upstream) instead of queueing into a dead engine.
* **Mid-stream failover** — each in-proc replica gets a *handoff*: when
  an engine's supervisor gives up (crash loop → DOWN) or a scheduler
  dies unsupervised, still-retryable requests are offered to the pool,
  which requeues the SAME request object on a sibling replica via
  ``engine.requeue_replay``. The client's stream queue and future carry
  over; admission re-prefills prompt + already-delivered tokens and the
  sampling-counter offset restores the seeded sample path, so the SSE
  stream continues byte-identically — no 5xx, no duplicate tokens.
* **Hedged unary retries** — :meth:`ReplicaPool.generate_sync` (and the
  async ``generate``) races a second replica when the primary is slow
  (jittered ``TPU_HEDGE_DELAY_S``) or retries when it fails fast; both
  spend from a token-bucket :class:`~gofr_tpu.serving.lifecycle.
  HedgeBudget` (``TPU_HEDGE_BUDGET``) so hedging can never double load
  on an already-slow tier, and are deadline-aware. Per-replica circuit
  breakers stay where they are — an open breaker's fast-fail is simply
  one more signal the router reroutes on, not a second breaker.
* **Active probing** — a jittered-interval prober issues one cheap
  synthetic generation per replica (``engine.synthetic_probe``: one
  greedy token through the full dataplane). A failed probe demotes the
  replica (routed around even if it still CLAIMS SERVING) and asks its
  supervisor to restart — recovery on evidence, not just on crash. A
  DOWN replica is revived and **re-admitted only after a passing
  probe**; a passing probe also resets the supervisor's crash-loop
  counter and half-opens a stuck circuit breaker.

* **Disaggregated prefill/decode tiers** (``TPU_REPLICA_ROLES``) —
  replicas tagged ``prefill`` run prompt prefill and ship the finished
  paged KV blocks (host bounce, ``ops/kv_cache.py`` export/import
  seam) to a ``decode`` replica, which inserts them into its radix
  prefix index and admission-aliases them zero-copy — chunked prefill
  stops stealing decode windows from latency-sensitive streams
  (DistServe/Splitwise). Robustness first: every transfer carries the
  request's ``Deadline`` plus a jittered-backoff retry budget
  (``TPU_TRANSFER_RETRIES``/``TPU_TRANSFER_TIMEOUT_S``), and every
  failure — prefill replica dying mid-transfer, a decode replica
  rejecting blocks, a corrupt payload, a whole tier with zero healthy
  replicas — degrades down a ladder that ends in fused serving on
  whatever survives, byte-identical and with one trace id, never a
  5xx for a retryable request (``docs/advanced-guide/resilience.md``).

Observability: ``app_tpu_replica_state`` (0=SERVING 1=DEGRADED
2=RESTARTING 3=DOWN per replica), ``app_tpu_failovers_total``,
``app_tpu_probe_failures_total``, ``app_tpu_hedged_requests_total``,
``app_tpu_tier_transfers_total{result}``,
``app_tpu_tier_transfer_seconds``, ``app_tpu_tier_mode`` (1 = tiered).

Determinism contract (the chaos suite, ``tests/test_replica_pool.py``):
clock/rng are injectable, the prober thread is optional (tests call
``probe_once()``), and nothing here sleeps on the request path.

Cross-replica replay only produces *byte-identical* continuations when
sibling replicas share params and the engine seed (the same
``TPU_SEED``); with distinct seeds the continuation is still a valid
sample path, just a different one.
"""

from __future__ import annotations

import concurrent.futures as cf
import random
import threading

import time
from typing import Any, Callable, Iterable, Optional, Sequence

from gofr_tpu.analysis import lockcheck
from gofr_tpu import faults
from gofr_tpu.errors import (
    ErrorDeadlineExceeded,
    ErrorNoHealthyReplica,
    ErrorTooManyRequests,
)
from gofr_tpu.serving.lifecycle import Deadline, HedgeBudget

#: Gauge encoding shared with app_tpu_engine_state.
_STATE_ORDER = {"SERVING": 0, "DEGRADED": 1, "RESTARTING": 2, "DOWN": 3}

#: Statuses a sibling replica may retry/hedge: per-replica overload or
#: failure. 4xx validation errors and 504 (the CALLER's deadline) are
#: the same on every replica and never rerouted.
_REROUTE_STATUSES = frozenset((429, 500, 502, 503))


def _is_reroutable(exc: BaseException) -> bool:
    return int(getattr(exc, "status_code", 500)) in _REROUTE_STATUSES


class Replica:
    """One pool member. Subclasses bind a concrete backend."""

    #: Streaming + request adoption need a backend that can continue a
    #: live stream handle: an in-process engine, or a remote replica
    #: consuming SSE (``HTTPReplica`` with streaming enabled).
    supports_stream = False
    #: True for network-backed replicas (remote-stream failover metric).
    remote = False
    #: Can this backend adopt a shipped KV-block payload
    #: (``import_prefilled``)? In-proc engines always; remote replicas
    #: when they stream AND carry an ops-port import service (the wire
    #: leg) — an import-incapable decode replica must not count toward
    #: tiered mode, or every transfer to it is a guaranteed-futile
    #: retry loop.
    supports_tier_import = False
    #: Can this backend receive DEVICE-resident block payloads (the
    #: zero-host-copy leg)? In-proc paged engines on the shared JAX
    #: runtime only — device arrays cannot cross a process boundary.
    supports_device_import = False
    #: Can this backend EXPORT prefilled blocks (honor
    #: ``set_tier_exporter``)? Same asymmetry guard on the prefill
    #: side: a prefill-tagged replica that can never ship blocks must
    #: not flip the pool tiered — it would pin fresh traffic to a
    #: replica that serves fused end-to-end while the real decode tier
    #: idles.
    supports_tier_export = False
    #: Can this backend redeem a transfer-server HANDLE
    #: (``ops.kv_cache.KVHandlePayload`` — the ``dma`` leg)? Remote
    #: replicas advertise it through health details (``tier_source.
    #: dma``); in-proc paged engines redeem loopback handles when the
    #: leg is pinned. A target without it simply never gets the dma
    #: rung — the ladder starts at device/wire for it.
    supports_dma_import = False
    #: Can this backend be PULLED FROM as a remote prefill source
    #: (``GET/POST /ops/tier-export``)? Remote prefill-role replicas
    #: whose health probe advertises ``tier_source.export`` — the
    #: multi-host reverse of ``supports_tier_export``, where the local
    #: decode engine asks a prefill pod for blocks it already computed
    #: instead of the pod pushing them.
    supports_tier_source = False

    def __init__(self, name: str, role: str = "fused") -> None:
        self.name = name
        # Disaggregated serving tier (TPU_REPLICA_ROLES): "prefill"
        # replicas run prompt prefill and ship the KV blocks, "decode"
        # replicas import blocks and stream tokens, "fused" (default)
        # serves both phases — and every role can serve fused when its
        # counterpart tier has no healthy replica (the degradation
        # ladder's last rung before 5xx).
        if role not in ("fused", "prefill", "decode"):
            raise ValueError(
                f"replica role must be fused|prefill|decode, got {role!r}"
            )
        self.role = role
        # Latched by a failed synthetic probe; cleared ONLY by a passing
        # one. While set, the router treats the replica as DOWN no
        # matter what its own state machine claims.
        self.probe_failed = False
        # Set by the pool's drain path (scaler scale-down, operator
        # retire): routing skips the replica immediately while in-flight
        # work runs to completion, then the pool closes and removes it.
        self.draining = False

    # -- routing surface ------------------------------------------------

    def state(self) -> str:
        raise NotImplementedError

    def load(self) -> int:
        """Outstanding work (queue + live); the least-loaded heuristic."""
        raise NotImplementedError

    def adapters(self) -> frozenset[str]:
        """LoRA adapter names this replica can serve RIGHT NOW (loaded
        weights). The router sends adapter-bound requests only to
        replicas advertising the adapter; in-proc replicas read their
        engine's live slot table, remote ones cache the set from the
        last health probe."""
        return frozenset()

    def load_adapter(self, name: str, source: Any) -> bool:
        """Ask this replica to load adapter ``name`` from ``source``
        (lazy reconciliation: the pool calls this when a request names
        an adapter no routable replica advertises). False when this
        backend cannot load adapters."""
        return False

    def set_handoff(self, handoff: Optional[Callable[[Any], bool]]) -> None:
        """Install/remove the pool's mid-stream failover target: the
        replica offers ``handoff(req)`` every still-retryable request it
        would otherwise fail terminally."""

    def throughput(self) -> float:
        """Measured tokens/sec (sliding window), 0.0 when unknown — the
        weighted router divides outstanding work by this to estimate
        completion time. Replicas without a signal (cold engines, unary
        HTTP backends) share a common floor, which degrades weighted
        routing to the plain least-loaded pick."""
        return 0.0

    def submit(self, prompt: Any, **kw: Any) -> Any:
        """Submit a generation; returns a ``_GenRequest``-shaped handle
        (``.future``, ``.stream``, ``.cancel_request()``)."""
        raise NotImplementedError

    def adopt(self, req: Any) -> bool:
        """Continue a salvaged request from a dying sibling (stream and
        future intact). False when this backend cannot."""
        return False

    def set_tier_exporter(self, exporter: Optional[Callable[..., bool]]) -> None:
        """Install/remove the pool's tier-transfer exporter on a
        prefill-role backend (no-op for backends without the seam)."""

    def import_prefilled(self, req: Any, payload: Any) -> Optional[str]:
        """Adopt a request whose prefill a sibling already computed,
        with its KV blocks as ``payload`` (None when the exporter had
        no paged pool). ``"imported"`` / ``"fused"`` on success (see
        ``engine.handoff_prefilled``), None when this backend cannot
        take it — remote replicas return None until a wire form of the
        block payload exists; the pool then tries another target or
        falls back to fused serving."""
        return None

    # -- probe surface ----------------------------------------------------

    def probe(self, timeout_s: float) -> tuple[str, str]:
        """One synthetic end-to-end check → ``(verdict, reason)`` with
        verdict ``"pass"`` (healthy), ``"busy"`` (overloaded — shedding
        or congested, which is a HEALTHY engine doing its job, never
        grounds for demotion or a restart), or ``"fail"`` (broken)."""
        raise NotImplementedError

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        """Attempt to bring a DOWN backend back for probation."""
        return False

    def note_probe_success(self) -> None:
        """Propagate a passing probe (supervisor counter reset, breaker
        half-open, ...)."""

    def notify_probe_failure(self, reason: str) -> None:
        """Propagate a failing probe (supervisor restart request)."""

    def mesh_topology(self) -> Optional[dict]:
        """The replica's serving-mesh shape (axes/devices) or ``None``
        when unsharded or unknown — in-proc replicas read their
        engine's mesh, remote ones cache it from the last health probe.
        The pool treats each sharded replica as ONE pod: dp across
        replicas, tp within each."""
        return None

    def headroom(self) -> Optional[float]:
        """The replica's HBM headroom ratio (device_telemetry's
        saturation signal) or ``None`` when unknown — in-proc replicas
        read their engine's ledger, remote ones cache it from the last
        health probe. The pool scaler treats low headroom like load
        pressure (``TPU_SCALE_UP_HEADROOM``)."""
        return None

    def slo_compliant(self) -> Optional[bool]:
        """Whether the replica's configured SLOs are currently within
        budget (every burn rate ≤ 1) AND its brownout ladder is below
        L3, ``None`` when unknown or no SLOs are configured — in-proc
        replicas read their engine's SLO engine, remote ones cache the
        health payload's ``slo`` detail from the last probe. ``pick()``
        deprioritizes ``False`` the way tier routing prefers roles."""
        return None

    def brownout_level(self) -> Optional[int]:
        """The replica's brownout-ladder level (``serving/brownout.py``)
        or ``None`` when unknown / the layer is off — in-proc replicas
        read their engine's controller, remote ones cache the health
        payload's ``brownout`` detail from the last probe. At L1+ the
        pool suppresses latency hedges and synthetic-probe generations
        against this replica; the scaler counts L2+ as pressure."""
        return None

    def control_pressure(self) -> Optional[int]:
        """The replica's control-plane scale-up advertisement
        (``serving/control_plane.py``: 1 while the host-overhead or
        predictive loop asserts pressure) or ``None`` when unknown /
        the plane is off — in-proc replicas read their engine, remote
        ones cache the health payload's ``control`` detail from the
        last probe. The scaler counts 1 as pressure
        (``TPU_SCALE_UP_CONTROL``)."""
        return None

    def describe(self) -> dict:
        return {
            "state": self.state(),
            "role": self.role,
            "probe_failed": self.probe_failed,
            "draining": self.draining,
            "load": self.load(),
            "supports_stream": self.supports_stream,
            "remote": self.remote,
            "adapters": sorted(self.adapters()),
            "mesh": self.mesh_topology(),
            "hbm_headroom": self.headroom(),
            "slo_compliant": self.slo_compliant(),
            "brownout_level": self.brownout_level(),
        }

    def close(self) -> None:
        pass


class EngineReplica(Replica):
    """An in-process :class:`InferenceEngine` (plus its supervisor)."""

    supports_stream = True
    supports_tier_import = True
    supports_tier_export = True

    def __init__(self, name: str, engine: Any, role: str = "fused") -> None:
        super().__init__(name, role)
        self.engine = engine
        # The engine's scheduler checks its OWN role at prefill
        # finalize, so the replica's role is mirrored down.
        engine.tier_role = role

    @property
    def supports_device_import(self) -> bool:  # type: ignore[override]
        """Device-leg target: a paged in-proc engine on this process's
        JAX runtime (the transfer ladder falls to host-bounce for
        unpaged engines — handing them a device payload would only be
        rejected at validation)."""
        return bool(getattr(self.engine, "kv_block", 0))

    @property
    def supports_dma_import(self) -> bool:  # type: ignore[override]
        """Loopback dma target: a paged in-proc engine can redeem a
        handle minted by this process's transfer server (the auto
        ladder never picks dma for in-proc targets — the device leg is
        strictly better there — but a ``TPU_TRANSFER_LEG=dma`` pin must
        be servable single-process so the rung is CI-testable and
        benchable without a second pod)."""
        return bool(getattr(self.engine, "kv_block", 0))

    def state(self) -> str:
        return str(self.engine.state)

    def load(self) -> int:
        eng = self.engine
        if getattr(eng, "family", "llm") != "llm":
            return 0
        # Lock-free host reads — a one-iteration-stale count is fine for
        # a routing heuristic.
        queued = eng._pending.qsize() + len(eng._wait_kv)
        live = sum(1 for s in eng._slots if s is not None)
        return queued + live + len(eng._prefilling)

    def throughput(self) -> float:
        # The engine's sliding-window AGGREGATE tokens/sec — the same
        # lifecycle.AggregateThroughput estimate its own projected-wait
        # shedder divides by. 0.0 while cold (no emissions in window).
        tput = getattr(self.engine, "_tput", None)
        if tput is None:
            return 0.0
        try:
            return float(tput.rate())
        except Exception:  # noqa: BLE001 — heuristic only, never break routing
            return 0.0

    def adapters(self) -> frozenset[str]:
        names = getattr(self.engine, "lora_names", None)
        if not callable(names):
            return frozenset()
        try:
            return frozenset(names())
        except Exception:  # noqa: BLE001 — advertisement is a routing hint only
            return frozenset()

    def mesh_topology(self) -> Optional[dict]:
        topo = getattr(self.engine, "mesh_topology", None)
        if not callable(topo):
            return None
        try:
            return topo()
        except Exception:  # noqa: BLE001 — advertisement is a debug hint only
            return None

    def headroom(self) -> Optional[float]:
        ratio = getattr(self.engine, "hbm_headroom_ratio", None)
        if not callable(ratio):
            return None
        try:
            return float(ratio())
        except Exception:  # noqa: BLE001 — advertisement is a routing hint only
            return None

    def slo_compliant(self) -> Optional[bool]:
        # engine.slo_compliant folds the brownout ladder in (L3 =
        # non-compliant) — the ONE routing signal pick() reads.
        check = getattr(self.engine, "slo_compliant", None)
        if callable(check):
            try:
                result = check()
            except Exception:  # noqa: BLE001 — advertisement is a debug hint only
                return None
            return None if result is None else bool(result)
        slo = getattr(self.engine, "_slo", None)
        if slo is None:
            return None
        try:
            return bool(slo.compliant())
        except Exception:  # noqa: BLE001 — advertisement is a debug hint only
            return None

    def brownout_level(self) -> Optional[int]:
        level = getattr(self.engine, "brownout_level", None)
        if not callable(level):
            return None
        try:
            n = level()
        except Exception:  # noqa: BLE001 — advertisement is a routing hint only
            return None
        return None if n is None else int(n)

    def control_pressure(self) -> Optional[int]:
        pressure = getattr(self.engine, "control_scale_pressure", None)
        if not callable(pressure):
            return None
        try:
            p = pressure()
        except Exception:  # noqa: BLE001 — advertisement is a routing hint only
            return None
        return None if p is None else int(p)

    def load_adapter(self, name: str, source: Any) -> bool:
        try:
            self.engine.load_lora(name, source)
            return True
        except Exception:  # noqa: BLE001 — reconciliation tries the next replica
            return False

    def set_handoff(self, handoff: Optional[Callable[[Any], bool]]) -> None:
        self.engine.set_replica_handoff(handoff)

    def set_tier_exporter(self, exporter: Optional[Callable[..., bool]]) -> None:
        self.engine.set_tier_exporter(exporter)

    def import_prefilled(self, req: Any, payload: Any) -> Optional[str]:
        if self.draining or self.probe_failed:
            return None
        if self.state() not in ("SERVING", "DEGRADED"):
            return None
        from gofr_tpu.ops.kv_cache import KVHandlePayload

        if isinstance(payload, KVHandlePayload):
            # dma leg, in-proc target: redeem the claim ticket HERE, on
            # the transfer path, not on the scheduler thread — a fetch
            # failure (stale key, dead server) raises DmaError out to
            # the pool's attempt loop, which bans the dma rung and
            # retries this same target one rung down with the inline
            # payload. The fetch carries the request's own deadline.
            from gofr_tpu.service.dma import dma_fetch

            payload = dma_fetch(
                payload, deadline=getattr(req, "deadline", None)
            )
        return self.engine.handoff_prefilled(req, payload)

    def submit(self, prompt: Any, **kw: Any) -> Any:
        return self.engine.submit_generate(prompt, **kw)

    def adopt(self, req: Any) -> bool:
        if req.adapter:
            # LoRA slot ids are PER-ENGINE: re-resolve the adapter name
            # against this engine's slot table (and its current load
            # generation) before requeueing — adopting under the dying
            # sibling's slot id would silently serve different weights.
            aid = getattr(self.engine, "_lora_names", {}).get(req.adapter)
            if aid is None:
                return False
            req.aid = aid
            req.lora_gen = self.engine._lora_gen[aid]
        if req.timeline is None and getattr(req, "traceparent", None):
            # A request born on a REMOTE replica has no local timeline
            # (the hub lives with the engine). Mint one on the adopting
            # engine under the caller's traceparent so the continuation
            # lands in the SAME trace the remote replica's spans joined.
            obs = getattr(self.engine, "_obs", None)
            if obs is not None:
                req.timeline = obs.begin(
                    prompt_tokens=len(req.prompt_ids),
                    traceparent=req.traceparent,
                    # Per-tenant SLO overrides judge at retirement from
                    # the timeline's tenant — an adopted request must
                    # not vanish from its tenant's burn windows.
                    tenant=str(getattr(req, "tenant", "") or ""),
                )
        return bool(self.engine.requeue_replay(req))

    def probe(self, timeout_s: float) -> tuple[str, str]:
        from gofr_tpu.errors import (
            ErrorDeadlineExceeded,
            ErrorTooManyRequests,
        )

        try:
            self.engine.synthetic_probe(timeout_s=timeout_s)
            return "pass", ""
        except (ErrorTooManyRequests, ErrorDeadlineExceeded) as exc:
            # Admission SHED the probe: overload, not breakage — a
            # replica answering 429s is exactly what load shedding is
            # for, and demoting/restarting it would cascade the load
            # onto its siblings until the whole pool restarts.
            return "busy", f"{type(exc).__name__}: {exc}"
        except cf.TimeoutError as exc:
            if self.load() > 1:
                # The probe queued behind real work: congested, not
                # dead. A wedged scheduler is the watchdog's job.
                return "busy", f"probe timed out behind {self.load()} waiting"
            return "fail", f"probe timed out on an idle engine: {exc}"
        except Exception as exc:  # noqa: BLE001 — ANY other failure demotes the replica
            return "fail", f"{type(exc).__name__}: {exc}"

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            return bool(sup.revive())
        try:
            self.engine.restart_sync()
            return True
        except Exception:  # noqa: BLE001 — a failed revive keeps the replica DOWN
            return False

    def note_probe_success(self) -> None:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            sup.note_probe_success()

    def notify_probe_failure(self, reason: str) -> None:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            sup.notify_probe_failure(reason)

    def close(self) -> None:
        self.engine.set_replica_handoff(None)
        self.engine.close()


class HTTPReplica(Replica):
    """A remote replica behind the service tier: generations via its
    OpenAI-compatible endpoint, liveness + capability advertisement via
    the health endpoint.

    With ``stream=True`` (the default — the remote is another gofr_tpu
    app) submissions consume the remote's SSE stream with the
    ``stream_options.include_tokens`` extension: every received chunk
    carries the raw token ids, which this side pushes into the local
    ``_GenRequest`` handle — so the pool's streaming surface works over
    the network, the delivered-token prefix is known EXACTLY, and a
    remote that dies or stalls mid-stream hands the request to a
    sibling via the pool handoff (in-proc siblings ``requeue_replay``
    it; greedy requests can also continue on another remote). Connect
    and read budgets are separate (``client.py``): a dead upstream
    fails fast at the handshake, a busy one is classified busy — never
    demoted — and an upstream that stops sending bytes for longer than
    ``idle_timeout_s`` mid-stream is treated as stalled and failed
    over.

    With ``stream=False`` the replica is unary-only (plain POST; any
    OpenAI-compatible upstream works) and streaming handles never route
    to it.

    Compose the service with :class:`CircuitBreakerConfig`/auth options
    at construction — the pool does not duplicate the breaker, it
    reroutes on its fast-fails and half-opens it on passing probes.
    """

    supports_stream = False  # instance attr set from ``stream=``
    remote = True

    def __init__(
        self,
        name: str,
        service: Any,
        *,
        generate_path: str = "v1/completions",
        health_path: str = ".well-known/health",
        stream: bool = True,
        tokenizer: Any = None,
        idle_timeout_s: float = 30.0,
        role: str = "fused",
        import_service: Any = None,
        import_path: str = "ops/tier-import",
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        super().__init__(name, role)
        self.service = service
        self.generate_path = generate_path
        self.health_path = health_path
        self.supports_stream = bool(stream) and hasattr(
            service, "stream_lines"
        )
        # Wire-leg tier transfers: an HTTPService pointed at the
        # remote's OPS port (TPU_REPLICA_OPS_ADDRS — the /ops/
        # tier-import endpoint lives next to /metrics and /debug/*,
        # off the serving dataplane). Without one, this replica cannot
        # adopt shipped blocks and never counts toward tiered mode.
        self._import_service = import_service
        self.import_path = import_path
        self.supports_tier_import = bool(
            self.supports_stream and import_service is not None
        )
        # Reverse direction of the same ops-port seam: a prefill-role
        # remote advertising tier_source in its health details can be
        # PULLED from (/ops/tier-export) — the local decode engine asks
        # it for blocks it already computed. Both flags are probe-fed
        # (unconditional-assign in probe()); until a probe sees the
        # advertisement the replica is neither a dma target nor a
        # source.
        self.export_path = "ops/tier-export"
        self._tier_source = False
        self._tier_dma = False
        self.tokenizer = tokenizer
        self.idle_timeout_s = float(idle_timeout_s)
        self._metrics = metrics
        self._logger = logger
        self._lock = lockcheck.make_lock("HTTPReplica._lock")
        self._inflight = 0
        self._state = "SERVING"
        self._adapters: frozenset[str] = frozenset()
        # Mesh topology and HBM headroom lifted from the last health
        # probe (None until a probe sees one): a remote pod advertises
        # its shape and saturation the same way an in-proc one does.
        self._mesh: Optional[dict] = None
        self._hbm_headroom: Optional[float] = None
        self._slo_compliant: Optional[bool] = None
        self._brownout_level: Optional[int] = None
        self._control_pressure: Optional[int] = None
        self._handoff: Optional[Callable[[Any], bool]] = None

    def state(self) -> str:
        return self._state

    def load(self) -> int:
        with self._lock:
            return self._inflight

    def adapters(self) -> frozenset[str]:
        return self._adapters

    def mesh_topology(self) -> Optional[dict]:
        return self._mesh

    def headroom(self) -> Optional[float]:
        return self._hbm_headroom

    def slo_compliant(self) -> Optional[bool]:
        return self._slo_compliant

    def brownout_level(self) -> Optional[int]:
        return self._brownout_level

    def control_pressure(self) -> Optional[int]:
        return self._control_pressure

    @property
    def supports_dma_import(self) -> bool:  # type: ignore[override]
        """dma-leg target: the remote's health probe advertised the
        handle protocol (``tier_source.dma`` — one codebase version
        speaks it both directions), and the ops-port import service is
        wired so the claim ticket has somewhere to land. Un-probed or
        older pods simply never get the dma rung."""
        return bool(self.supports_tier_import and self._tier_dma)

    @property
    def supports_tier_source(self) -> bool:  # type: ignore[override]
        """Pull-source capability: the remote advertised
        ``tier_source.export`` and this side holds an ops-port service
        to ask through. The pool additionally requires the prefill role
        and routability before pulling."""
        return bool(self._tier_source and self._import_service is not None)

    def set_handoff(self, handoff: Optional[Callable[[Any], bool]]) -> None:
        self._handoff = handoff

    # -- submit ---------------------------------------------------------

    def _prompt_ids(self, prompt: Any) -> list[int]:
        """Token ids for the request handle. A known id list is the
        failover precondition: the delivered prefix can only be resumed
        on a sibling when prompt + continuation are ids. String prompts
        encode through the shared tokenizer when one was provided;
        without one the request still serves, it just cannot fail over
        mid-stream."""
        if not isinstance(prompt, str):
            return [int(t) for t in prompt]
        if self.tokenizer is not None:
            try:
                return [int(t) for t in self.tokenizer.encode(prompt)]
            except Exception:  # noqa: BLE001 — serve anyway, without failover rights
                return []
        return []

    def submit(self, prompt: Any, **kw: Any) -> Any:
        from gofr_tpu.serving.types import _GenRequest

        prompt_ids = self._prompt_ids(prompt)
        req = _GenRequest(
            prompt_ids=prompt_ids,
            max_new_tokens=int(kw.get("max_new_tokens", 128)),
            temperature=float(kw.get("temperature", 0.0)),
            stop_on_eos=bool(kw.get("stop_on_eos", True)),
            top_p=float(kw.get("top_p", 1.0)),
            stop_texts=list(kw.get("stop") or []),
            seed=int(kw["seed"]) & 0x7FFFFFFF if kw.get("seed") is not None
            else 0,
            adapter=str(kw.get("adapter") or ""),
            tenant=str(kw.get("tenant") or ""),
            slo_class=str(kw.get("slo_class") or "standard"),
            pin_replica=bool(kw.get("pin_replica", False)),
            # The FULL sampling contract rides the local handle too, not
            # just the wire body: a failover adoption (in-proc
            # requeue_replay or remote re-submit) continues from this
            # request, and a sibling missing logit_bias/penalties would
            # silently sample different tokens.
            frequency_penalty=float(kw.get("frequency_penalty") or 0.0),
            presence_penalty=float(kw.get("presence_penalty") or 0.0),
            logit_bias={
                int(k): float(v)
                for k, v in (kw.get("logit_bias") or {}).items()
            },
            top_logprobs=int(kw.get("top_logprobs") or 0),
        )
        if kw.get("deadline") is not None:
            req.deadline = kw["deadline"]
        if kw.get("cancel") is not None:
            req.cancel = kw["cancel"]
        # Cross-replica trace stitching AND post-failover timeline
        # minting both need the caller's trace context on the request.
        req.traceparent = kw.get("traceparent")
        # Sampled streams can only resume byte-identically on a sibling
        # when the sample path is pinned by a CALLER-chosen seed; an
        # upstream-drawn seed never leaves the remote.
        req.remote_seeded = kw.get("seed") is not None
        deadline = kw.get("deadline")
        with self._lock:
            self._inflight += 1
        worker = threading.Thread(
            target=self._run_stream if self.supports_stream
            else self._run_unary,
            args=(req, prompt, kw, deadline),
            name=f"http-replica-{self.name}",
            daemon=True,
        )
        worker.start()
        return req

    # -- wire helpers ----------------------------------------------------

    @staticmethod
    def _sampling_body(prompt: Any, kw: dict, stream: bool) -> dict:
        """The generation body with the FULL sampling contract: a remote
        replica that silently dropped logit_bias/penalties/adapter would
        serve differently-sampled (or base-model) output with a 200."""
        body: dict[str, Any] = {
            "prompt": prompt,
            "max_tokens": int(kw.get("max_new_tokens", 128)),
            "temperature": float(kw.get("temperature", 0.0)),
            "stream": bool(stream),
        }
        for src, dst in (
            ("top_p", "top_p"), ("stop", "stop"),
            ("logit_bias", "logit_bias"),
            ("frequency_penalty", "frequency_penalty"),
            ("presence_penalty", "presence_penalty"),
            ("top_logprobs", "top_logprobs"),
            # A loaded LoRA adapter's name IS a model on the OpenAI
            # surface (this repo's own openai_compat convention).
            ("adapter", "model"),
        ):
            if kw.get(src):
                body[dst] = kw[src]
        # seed=0 is a VALID explicit seed, not an absence: a truthiness
        # filter would drop it from the wire while remote_seeded still
        # marks the request resumable — the sibling would then re-walk
        # the prefix on a different sample path than the remote took.
        if kw.get("seed") is not None:
            body["seed"] = kw["seed"]
        return body

    @staticmethod
    def _request_headers(
        kw: dict, deadline: Optional[Deadline]
    ) -> dict[str, str]:
        headers: dict[str, str] = {}
        if deadline is not None:
            headers["X-Request-Timeout"] = str(
                max(deadline.remaining(), 0.001)
            )
        if kw.get("tenant"):
            headers["X-Tenant-Id"] = str(kw["tenant"])
        if kw.get("slo_class"):
            # Brownout priority class rides the wire so the remote's
            # OWN controller sheds batch-first there too.
            headers["X-SLO-Class"] = str(kw["slo_class"])
        if kw.get("traceparent"):
            # Cross-replica trace stitching: the remote replica's server
            # middleware adopts this trace id, so its spans land in the
            # SAME trace as the routing tier's.
            headers["traceparent"] = str(kw["traceparent"])
        return headers

    # -- streaming (SSE) -------------------------------------------------

    def _run_stream(
        self, req: Any, prompt: Any, kw: dict, deadline: Optional[Deadline]
    ) -> None:
        """Worker: consume the remote SSE stream into the local request
        handle. Token ids ride every chunk (``include_tokens``), so the
        handle's ``token_ids`` IS the delivered prefix at any instant —
        the failover precondition. Terminal paths: [DONE] after a
        finish chunk resolves the future; a transport loss, stall past
        the idle budget, or truncation offers the request to the pool
        handoff; a request-shaped upstream error fails it untouched."""
        import json as jsonlib

        body = self._sampling_body(prompt, kw, stream=True)
        body["stream_options"] = {"include_tokens": True}
        headers = self._request_headers(kw, deadline)
        start = time.monotonic()
        first_at: Optional[float] = None
        reason = "stop"
        prompt_tokens = len(req.prompt_ids)
        text_parts: list[str] = []
        done_seen = False
        finish_seen = False
        remote_brownout = False
        try:
            with self.service.stream_lines(
                "POST", self.generate_path, json=body, headers=headers,
                read_timeout_s=self.idle_timeout_s,
            ) as lines:
                for line in lines:
                    if req.cancel.cancelled or req.future.cancelled():
                        # Caller is gone: closing the connection cancels
                        # the remote generation (its disconnect watcher)
                        # — no failover for a stream nobody wants.
                        self._finish_stream(req, None, cancelled=True)
                        return
                    if not line.startswith("data:"):
                        continue  # SSE comments / keepalive heartbeats
                    data = line[len("data:"):].strip()
                    if not data:
                        continue
                    if data == "[DONE]":
                        done_seen = True
                        break
                    try:
                        event = jsonlib.loads(data)
                    except ValueError:
                        continue  # malformed frame: ignore, watch framing
                    err = event.get("error")
                    if isinstance(err, dict):
                        exc = self._upstream_error(err)
                        raise exc
                    choices = event.get("choices") or []
                    if not choices:
                        continue  # usage-only chunk
                    choice = choices[0]
                    toks = choice.get("token_ids") or []
                    if toks and first_at is None:
                        first_at = time.monotonic()
                    for tok in toks:
                        req.token_ids.append(int(tok))
                        req.stream.put(int(tok))
                    text = choice.get("text")
                    if text is None:
                        text = (choice.get("delta") or {}).get("content")
                    if text:
                        text_parts.append(str(text))
                    if choice.get("finish_reason"):
                        reason = str(choice["finish_reason"])
                        finish_seen = True
                        # The remote's brownout-clamp advertisement
                        # (finish-chunk field) survives the hop.
                        if choice.get("brownout"):
                            remote_brownout = True
                        # On an ADOPTED continuation the upstream's
                        # prompt was prompt+delivered, so its reported
                        # prompt_tokens would double-count the delivered
                        # prefix — keep the original prompt length then.
                        if "prompt_tokens" in choice and not req.replays:
                            prompt_tokens = int(choice["prompt_tokens"])
            if not (done_seen and finish_seen):
                # EOF without terminal framing: the upstream vanished
                # mid-stream (truncated SSE). Retryable replica loss.
                from gofr_tpu.errors import ErrorServiceUnavailable

                exc = ErrorServiceUnavailable(
                    f"replica {self.name} stream truncated after "
                    f"{len(req.token_ids)} token(s)"
                )
                exc.kind = "read"  # type: ignore[attr-defined]
                raise exc
        except Exception as exc:  # noqa: BLE001 — classified below, never dropped
            self._on_stream_loss(req, exc)
            return
        finally:
            with self._lock:
                self._inflight -= 1
        from gofr_tpu.serving.types import GenerationResult

        text = "".join(text_parts)
        if self.tokenizer is not None and req.token_ids and (
            not text or req.replays
        ):
            # A replayed (adopted) continuation's text_parts cover only
            # the post-failover tokens — the authoritative text is the
            # decode of the FULL delivered id sequence.
            try:
                text = self.tokenizer.decode(req.token_ids)
            except Exception:  # noqa: BLE001 — text is best-effort on the id wire
                pass
        result = GenerationResult(
            text=text,
            token_ids=list(req.token_ids),
            prompt_tokens=prompt_tokens,
            ttft_s=(first_at - start) if first_at is not None else 0.0,
            duration_s=time.monotonic() - start,
            finish_reason=reason,
            brownout=remote_brownout,
        )
        self._finish_stream(req, result)

    def _finish_stream(
        self, req: Any, result: Any, cancelled: bool = False
    ) -> None:
        """Resolve the local handle exactly once (future first, then the
        stream sentinel so consumers draining the stream see the end
        AFTER the result exists). A cancelled request resolves with the
        same typed error the in-proc scheduler's reap uses, so a caller
        blocked on the future fails promptly instead of timing out."""
        if cancelled and not req.future.done():
            from gofr_tpu.errors import ErrorRequestCancelled

            try:
                req.future.set_exception(ErrorRequestCancelled())
            except Exception:  # noqa: BLE001 — future cancelled concurrently
                pass
        if result is not None and not req.future.done():
            try:
                req.future.set_result(result)
            except Exception:  # noqa: BLE001 — future cancelled concurrently
                pass
        timeline = getattr(req, "timeline", None)
        if timeline is not None:
            if cancelled:
                timeline.finish("cancelled")
            elif result is not None:
                timeline.finish(
                    "ok", result.finish_reason, len(req.token_ids)
                )
        req.stream.put(None)

    @staticmethod
    def _upstream_error(err: dict) -> Exception:
        """Terminal SSE error event → typed exception carrying the
        upstream's status code (so reroute-vs-propagate classification
        matches the unary path)."""
        from gofr_tpu.errors import GofrError

        exc = GofrError(str(err.get("message", "upstream stream error")))
        try:
            exc.status_code = int(err.get("code", 500))
        except (TypeError, ValueError):
            exc.status_code = 500
        return exc

    def _on_stream_loss(self, req: Any, exc: BaseException) -> None:
        """A stream died before its terminal framing. Replica-shaped
        losses (connect/read/transport, 5xx, truncation) offer the
        request to the pool handoff — a sibling resumes from the
        delivered-token prefix, the client never notices. Request-shaped
        errors (4xx) and non-resumable requests fail honestly."""
        handoff = self._handoff
        resumable = (
            handoff is not None
            and not req.pin_replica
            and _is_reroutable(exc)
            and req.retryable()
            # The delivered prefix is only reconstructable as ids.
            and bool(req.prompt_ids)
            # Sampled continuations are only byte-identical when the
            # sample path is pinned by an EXPLICIT seed the sibling can
            # re-walk; an upstream-drawn seed is unknown here.
            and (req.temperature == 0.0 or getattr(req, "remote_seeded",
                                                   req.seed != 0))
        )
        if resumable:
            try:
                if handoff(req):
                    if self._logger is not None:
                        self._logger.warnf(
                            "remote replica %s lost its stream (%s); "
                            "request resumed on a sibling (%d token(s) "
                            "already delivered)",
                            self.name, exc, len(req.token_ids),
                        )
                    return
            except Exception as handoff_exc:  # noqa: BLE001 — fall through to terminal fail
                if self._logger is not None:
                    self._logger.errorf(
                        "stream handoff from %s failed: %s",
                        self.name, handoff_exc,
                    )
        timeline = getattr(req, "timeline", None)
        if timeline is not None:
            timeline.finish("error", type(exc).__name__)
        try:
            if not req.future.done():
                req.future.set_exception(exc)
        except Exception:  # noqa: BLE001 — future cancelled concurrently
            pass
        req.stream.put(None)

    def adopt(self, req: Any) -> bool:
        """Continue a salvaged request on THIS remote: re-submit the
        prompt plus the already-delivered continuation as a token-id
        prompt (the OpenAI surface accepts id arrays) and keep filling
        the SAME stream/future. Greedy-only: a remote cannot restore a
        sampling counter mid-path, and re-walking a sampled prefix over
        the wire is not byte-exact. Stop-sequence requests also stay
        in-proc: a match spanning the failover boundary (delivered text
        ends mid-sequence) is invisible to a remote that only scans its
        OWN generated text. In-proc siblings re-decode the full history
        and handle both."""
        if not self.supports_stream or not req.retryable():
            return False
        if req.temperature != 0.0 or not req.prompt_ids:
            return False
        if req.stop_texts:
            return False
        if req.adapter and req.adapter not in self._adapters:
            return False
        if self._state != "SERVING" or self.probe_failed or self.draining:
            return False
        req.replays += 1
        remaining = req.max_new_tokens - len(req.token_ids)
        if remaining <= 0:
            from gofr_tpu.serving.types import GenerationResult

            text = ""
            if self.tokenizer is not None and req.token_ids:
                try:
                    text = self.tokenizer.decode(req.token_ids)
                except Exception:  # noqa: BLE001 — text is best-effort on the id wire
                    pass
            self._finish_stream(req, GenerationResult(
                text=text, token_ids=list(req.token_ids),
                prompt_tokens=len(req.prompt_ids), ttft_s=0.0,
                duration_s=0.0, finish_reason="length",
            ))
            return True
        kw: dict[str, Any] = {
            "max_new_tokens": remaining,
            "temperature": req.temperature,
            "top_p": req.top_p,
            "adapter": req.adapter,
            "tenant": req.tenant,
            "traceparent": getattr(req, "traceparent", None),
            # The rest of the sampling contract rides along: dropping
            # penalties/bias would continue on different logits.
            "frequency_penalty": req.frequency_penalty,
            "presence_penalty": req.presence_penalty,
            "logit_bias": dict(req.logit_bias),
            "top_logprobs": req.top_logprobs,
        }
        if req.seed:
            kw["seed"] = req.seed
        with self._lock:
            self._inflight += 1
        worker = threading.Thread(
            target=self._run_stream,
            args=(
                req, list(req.prompt_ids) + list(req.token_ids), kw,
                req.deadline,
            ),
            name=f"http-replica-{self.name}-adopt",
            daemon=True,
        )
        worker.start()
        return True

    def import_prefilled(self, req: Any, payload: Any) -> Optional[str]:
        """Wire-leg tier transfer: ship the exported KV blocks to the
        remote decode replica's ops-port import endpoint (length-
        prefixed binary body, the client's separate connect/read
        budgets — GL012), then drive the ORIGINAL request handle over
        the ordinary streaming submit so the remote's admission aliases
        the just-imported blocks zero-copy.

        The two legs fail independently, and every combination degrades
        without a 5xx or a second trace:

        * import POST rejected (non-2xx / ``"fused"`` reply: corrupt
          body, stale fingerprint, remote without a paged pool) → the
          request still streams there and re-prefills — ``"fused"``;
        * import POST dies mid-wire (read loss) → same ``"fused"``
          adoption: the stream leg decides whether the remote is
          actually alive, and a mid-stream death hands the request to
          the pool handoff like any remote stream loss (one trace id);
        * nothing listening at the ops port (connect-refused) → None:
          the remote is gone, the pool excludes it and tries the next
          target or falls down the ladder.

        Returns None (not adoptable here) for non-streaming replicas,
        requests that already delivered tokens (transfers ship FRESH
        prefills), sampled requests without a caller-pinned seed (the
        remote cannot re-walk an unseeded sample path byte-exactly),
        adapters this replica does not advertise, and replicas outside
        routable state — the pool then tries elsewhere."""
        if not self.supports_tier_import or not req.retryable():
            return None
        if req.token_ids or req.pin_replica:
            return None
        # The forwarded trace context: an explicit caller traceparent
        # when the request carried one, else the header form of the
        # request's own timeline — the remote's spans and flight record
        # must join THIS trace either way (the one-trace contract).
        traceparent = getattr(req, "traceparent", None)
        if not traceparent and getattr(req, "timeline", None) is not None:
            traceparent = req.timeline.traceparent()
        if req.temperature != 0.0 and not (
            req.seed or getattr(req, "remote_seeded", False)
        ):
            return None
        if req.adapter and req.adapter not in self._adapters:
            return None
        if self._state != "SERVING" or self.probe_failed or self.draining:
            return None
        verdict = "fused"
        if payload is not None:
            from gofr_tpu.ops.kv_cache import (
                KVHandlePayload,
                handle_to_wire,
                payload_to_wire,
            )
            from gofr_tpu.service.dma import DmaError

            # dma leg: the POST carries only the claim ticket; the
            # remote redeems it with a direct fetch from the exporter's
            # transfer server. Inline (wire leg) otherwise.
            is_handle = isinstance(payload, KVHandlePayload)
            body = (
                handle_to_wire(payload) if is_handle
                else payload_to_wire(payload)
            )
            headers = {"Content-Type": "application/octet-stream"}
            if traceparent:
                headers["traceparent"] = str(traceparent)
            try:
                resp = self._import_service.post(
                    self.import_path, body=body, headers=headers,
                )
                if resp.status_code < 400 and (
                    resp.json().get("result") == "imported"
                ):
                    verdict = "imported"
                elif is_handle:
                    # The remote could not REDEEM the ticket (stale
                    # key, fetch failure on its side, geometry drift).
                    # Unlike a rejected inline body, a strictly better
                    # rung exists on this SAME target — the wire POST
                    # ships the actual bytes — so raise instead of
                    # adopting fused: the pool bans the dma rung and
                    # retries here one rung down.
                    raise DmaError(
                        f"remote {self.name} did not redeem the dma "
                        f"handle (http {resp.status_code})",
                        kind="stale",
                    )
                elif self._logger is not None:
                    self._logger.warnf(
                        "wire tier import to %s rejected (%d); the "
                        "request will re-prefill there",
                        self.name, resp.status_code,
                    )
            except DmaError:
                raise
            except Exception as exc:  # noqa: BLE001 — every wire failure has a fused/ladder fallback
                if getattr(exc, "kind", "") == "connect":
                    # Nothing listening: the remote is dead, not merely
                    # rejecting — let the pool try another target.
                    return None
                if is_handle:
                    # A handle POST that died mid-wire shipped nothing:
                    # rung descent (retry via wire), never a fused
                    # adoption that silently forfeits the transfer.
                    raise
                if self._logger is not None:
                    self._logger.warnf(
                        "wire tier import to %s failed mid-POST (%s); "
                        "adopting the request fused", self.name, exc,
                    )
        # Adopt the request: the same worker-thread SSE consumption as
        # a fresh submit, driving the caller's existing stream/future —
        # mid-stream death from here on follows the ordinary remote-
        # stream failover path (pool handoff, one trace id).
        kw: dict[str, Any] = {
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature,
            "top_p": req.top_p,
            "stop": list(req.stop_texts),
            "adapter": req.adapter,
            "tenant": req.tenant,
            "slo_class": req.slo_class,
            "traceparent": traceparent,
            "frequency_penalty": req.frequency_penalty,
            "presence_penalty": req.presence_penalty,
            "logit_bias": dict(req.logit_bias),
            "top_logprobs": req.top_logprobs,
        }
        if req.seed or getattr(req, "remote_seeded", False):
            kw["seed"] = req.seed
        with self._lock:
            self._inflight += 1
        worker = threading.Thread(
            target=self._run_stream,
            args=(req, list(req.prompt_ids), kw, req.deadline),
            name=f"http-replica-{self.name}-import",
            daemon=True,
        )
        worker.start()
        return verdict

    def fetch_prefilled(
        self,
        token_ids: "list[int]",
        *,
        deadline: Optional[Deadline] = None,
        timeout_s: float = 2.0,
        traceparent: Optional[str] = None,
        mode: str = "dma",
    ) -> Any:
        """Remote prefill-source pull: ask this replica's ops port for
        the longest cached prefix of ``token_ids`` (``POST
        /ops/tier-export`` — the tier-import codec run in reverse).
        Returns the decoded payload — a ``KVHandlePayload`` claim
        ticket in ``mode="dma"``, the inline ``KVBlockPayload`` in
        ``mode="wire"`` — or None on a miss/unsupported reply. The
        budget (``timeout_s`` clamped to ``deadline``) travels IN the
        request so the remote's own radix-walk wait is bounded by it
        too, not just our socket read. Transport errors propagate
        (typed, ``kind``-tagged) — the pool's pull loop degrades them
        one rung at a time, terminally to local prefill."""
        if self._import_service is None:
            return None
        budget = float(timeout_s)
        if deadline is not None:
            budget = min(budget, float(deadline.remaining()))
        if budget <= 0:
            return None
        headers: dict[str, str] = {}
        if traceparent:
            headers["traceparent"] = str(traceparent)
        resp = self._import_service.post(
            self.export_path,
            json={
                "token_ids": [int(t) for t in token_ids],
                "mode": mode,
                "timeout_s": budget,
            },
            headers=headers,
        )
        body = resp.body or b""
        if resp.status_code >= 400 or len(body) < 4:
            return None
        from gofr_tpu.ops.kv_cache import (
            HANDLE_MAGIC,
            WIRE_MAGIC,
            handle_from_wire,
            payload_from_wire,
        )

        if body[:4] == HANDLE_MAGIC:
            return handle_from_wire(body)
        if body[:4] == WIRE_MAGIC:
            return payload_from_wire(body)
        return None  # JSON miss/unsupported reply

    def _run_unary(
        self, req: Any, prompt: Any, kw: dict, deadline: Optional[Deadline]
    ) -> None:
        from gofr_tpu.errors import ErrorServiceUnavailable
        from gofr_tpu.serving.types import GenerationResult

        start = time.monotonic()
        try:
            body = self._sampling_body(prompt, kw, stream=False)
            headers = self._request_headers(kw, deadline)
            resp = self.service.post(
                self.generate_path, json=body, headers=headers
            )
            if resp.status_code >= 400:
                if resp.status_code == 429:
                    raise ErrorTooManyRequests(
                        f"replica {self.name} shed the request",
                        retry_after_s=float(
                            resp.get_header("Retry-After") or 1.0
                        ),
                    )
                if resp.status_code >= 500:
                    raise ErrorServiceUnavailable(
                        f"replica {self.name} answered {resp.status_code}"
                    )
                # Request-shaped 4xx (400/404/413/...): surface the
                # UPSTREAM's status untouched — the request would fail
                # identically on every replica, so it must not become a
                # reroutable 503 and bounce around the pool.
                from gofr_tpu.errors import GofrError

                exc = GofrError(
                    f"replica {self.name} answered {resp.status_code}: "
                    f"{resp.body[:200].decode(errors='replace')}"
                )
                exc.status_code = resp.status_code
                raise exc
            data = resp.json()
            if isinstance(data, dict) and "choices" not in data:
                data = data.get("data", data)  # unwrap gofr envelopes
            choice = (data.get("choices") or [{}])[0]
            usage = data.get("usage") or {}
            result = GenerationResult(
                text=str(choice.get("text", "")),
                token_ids=[],
                prompt_tokens=int(usage.get("prompt_tokens", 0)),
                ttft_s=0.0,
                duration_s=time.monotonic() - start,
                finish_reason=str(choice.get("finish_reason", "stop")),
                # The remote's brownout-clamp advertisement rides
                # through: clients of a multi-host pool must still see
                # that the truncation was policy, not a bug.
                brownout=bool(choice.get("brownout", False)),
            )
            if not req.future.done():
                req.future.set_result(result)
        except Exception as exc:  # noqa: BLE001 — every failure must reach the caller
            try:
                if not req.future.done():
                    req.future.set_exception(exc)
            except Exception:  # noqa: BLE001 — future cancelled concurrently
                pass
        finally:
            with self._lock:
                self._inflight -= 1
            req.stream.put(None)

    def probe(self, timeout_s: float) -> tuple[str, str]:
        """Health probe with dead-vs-busy classification and capability
        refresh. Separate connect/read budgets (``client.py``) make the
        distinction observable: a CONNECT failure means nothing is
        listening (fail → demote), while a READ timeout behind queued
        work means a live upstream busy serving (busy → leave routing
        alone; restarting a loaded replica would cascade its load onto
        the siblings). The health payload's ``lora_adapters`` detail
        refreshes the advertised adapter set the router filters on."""
        try:
            health = self._fetch_health()
        except Exception as exc:  # noqa: BLE001 — classified below
            kind = getattr(exc, "kind", "")
            if kind == "read" and self.load() > 0:
                # The upstream accepted the connection but answered
                # slowly BEHIND real queued work: congested, not dead.
                return "busy", (
                    f"health read timed out behind {self.load()} "
                    f"in-flight request(s)"
                )
            self._state = "DOWN"
            return "fail", f"{type(exc).__name__}: {exc}"
        details = health.get("details") or {}
        adapters = details.get("lora_adapters")
        if isinstance(adapters, (list, tuple, set, frozenset)):
            self._adapters = frozenset(str(a) for a in adapters)
        # Assign unconditionally: a remote pod restarted UNSHARDED
        # omits the mesh key entirely, and a stale tp topology kept
        # advertising forever would mislead the operator's fleet view.
        mesh = details.get("mesh")
        self._mesh = dict(mesh) if isinstance(mesh, dict) else None
        # Same unconditional-assign discipline for the saturation
        # signal: a restarted remote without a ledger clears it.
        ledger = details.get("hbm_ledger")
        ratio = (
            ledger.get("headroom_ratio")
            if isinstance(ledger, dict) else None
        )
        self._hbm_headroom = (
            float(ratio) if isinstance(ratio, (int, float)) else None
        )
        # SLO advertisement rides the same unconditional-assign
        # discipline: a restarted remote without objectives clears it.
        slo = details.get("slo")
        compliant = (
            slo.get("compliant") if isinstance(slo, dict) else None
        )
        self._slo_compliant = (
            bool(compliant) if isinstance(compliant, bool) else None
        )
        # Brownout advertisement (serving/brownout.py): the remote's
        # ladder level, so this pool suppresses hedges/probes against a
        # browning-out pod and deprioritizes it at L3 — same
        # unconditional-assign discipline.
        brownout = details.get("brownout")
        level = (
            brownout.get("level") if isinstance(brownout, dict) else None
        )
        self._brownout_level = (
            int(level) if isinstance(level, (int, float)) else None
        )
        # Control-plane advertisement (serving/control_plane.py): the
        # remote's scale-pressure bit, so this pool's scaler sees the
        # host-overhead/predictive verdict — same unconditional-assign
        # discipline (a probe after the remote disabled its plane must
        # clear the cached flag, not hold it forever).
        control = details.get("control")
        pressure = (
            control.get("scale_pressure")
            if isinstance(control, dict) else None
        )
        self._control_pressure = (
            int(pressure) if isinstance(pressure, (int, float)) else None
        )
        # Multi-host disaggregation advertisement: can this pod be
        # pulled from as a prefill source (/ops/tier-export), and does
        # it speak the KVH1 handle protocol (the dma leg)? Same
        # unconditional-assign discipline — a pod restarted without a
        # paged pool stops being a source/dma target on the next probe.
        tier_source = details.get("tier_source")
        self._tier_source = bool(
            tier_source.get("export")
            if isinstance(tier_source, dict) else False
        )
        self._tier_dma = bool(
            tier_source.get("dma")
            if isinstance(tier_source, dict) else False
        )
        if (
            self._brownout_level is not None
            and self._brownout_level >= 3
            and self._slo_compliant is not False
        ):
            # L3 means the remote marked itself non-routable even if
            # its own burn gauges momentarily read compliant.
            self._slo_compliant = False
        if health.get("status") == "UP":
            self._state = "SERVING"
            return "pass", ""
        self._state = "DOWN"
        return "fail", str(details.get("error", "DOWN"))

    def _fetch_health(self) -> dict:
        """GET the rich health endpoint (engine state + adapter set);
        raises on transport failure so :meth:`probe` can classify the
        error kind. The gofr ``/.well-known/health`` aggregate nests the
        engine's check under ``details.tpu`` — when present, THAT status
        governs (a remote whose redis is down still serves tokens) and
        its details (``lora_adapters``, engine state) are lifted. Falls
        back to the service's liveness check when the rich endpoint
        404s (non-gofr upstreams)."""
        get = getattr(self.service, "get", None)
        if not callable(get) or not self.health_path:
            return self.service.health_check()
        resp = get(self.health_path)
        if resp.status_code == 404:
            return self.service.health_check()
        body: Any = None
        try:
            body = resp.json()
        except Exception:  # noqa: BLE001 — non-JSON health body
            body = None
        if isinstance(body, dict) and isinstance(body.get("data"), dict):
            body = body["data"]  # gofr envelope
        if not isinstance(body, dict):
            body = {}
        details = body.get("details")
        details = dict(details) if isinstance(details, dict) else {}
        tpu = details.get("tpu")
        if isinstance(tpu, dict):
            # The serving datasource's own check wins: it carries the
            # engine state machine and the loaded adapter set.
            status = "UP" if tpu.get("status") == "UP" else "DOWN"
            inner = tpu.get("details")
            return {
                "status": status,
                "details": dict(inner) if isinstance(inner, dict) else {},
            }
        if resp.status_code >= 400:
            details.setdefault("error", f"status {resp.status_code}")
            return {"status": "DOWN", "details": details}
        status = str(body.get("status") or "UP")
        return {
            "status": "UP" if status == "UP" else "DOWN",
            "details": details,
        }

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        verdict, _ = self.probe(timeout_s=probe_timeout_s)
        return verdict == "pass"

    def note_probe_success(self) -> None:
        # Half-open a stuck breaker anywhere in the option chain: the
        # probe proved the address serves again (circuit_breaker.py).
        svc = self.service
        while svc is not None:
            hook = getattr(svc, "note_probe_success", None)
            if callable(hook):
                hook()
            svc = getattr(svc, "_inner", None)

    def close(self) -> None:
        for svc in (self.service, self._import_service):
            close = getattr(svc, "close", None)
            if callable(close):
                close()


class ReplicaPool:
    """Engine-shaped facade over N replicas (drop-in for
    ``container.tpu``: the OpenAI routes and both gRPC servicers serve
    through it unchanged)."""

    def __init__(
        self,
        replicas: Sequence[Replica],
        *,
        # Hedge only requests slower than a typical healthy completion:
        # multi-token generations run seconds, and a sub-second default
        # would hedge nearly EVERY request on a healthy pool.
        hedge_delay_s: float = 2.0,
        hedge_budget: Optional[HedgeBudget] = None,
        probe_interval_s: float = 30.0,
        probe_timeout_s: float = 30.0,
        weighted: bool = True,
        # Disaggregated-tier transfer budget (TPU_TRANSFER_RETRIES /
        # TPU_TRANSFER_TIMEOUT_S): extra import attempts after the
        # first, the overall wall-clock bound, and the jittered-
        # exponential backoff base between attempts.
        transfer_retries: int = 2,
        transfer_timeout_s: float = 10.0,
        transfer_backoff_s: float = 0.05,
        # Transfer-leg pin (TPU_TRANSFER_LEG): "" = automatic ladder
        # (dma → device/wire → host-bounce per target), or exactly one
        # of "dma" / "device" / "wire" / "host" to pin every transfer
        # to that leg (targets that cannot serve it are skipped; the
        # fused degradation rungs below the ladder are unchanged).
        transfer_leg: str = "",
        # Remote prefill-source pull budget (TPU_SOURCE_TIMEOUT_S):
        # wall-clock bound on asking a prefill-role remote for cached
        # blocks before a fresh request admits locally; 0 disables the
        # pull plane entirely.
        source_timeout_s: float = 2.0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        if not replicas:
            raise ValueError("a replica pool needs at least one replica")
        self._replicas = list(replicas)
        # Weighted routing (TPU_ROUTE_WEIGHTED, default on): pick by
        # least ESTIMATED COMPLETION TIME — outstanding work over the
        # replica's measured tokens/sec — instead of raw queue length,
        # so a replica decoding 2× faster absorbs ~2× the traffic.
        # Replicas with no throughput signal share a common default, in
        # which case the pick degrades to exactly the least-loaded one.
        self.weighted = bool(weighted)
        self.hedge_delay_s = max(0.0, float(hedge_delay_s))
        self.hedge_budget = (
            hedge_budget if hedge_budget is not None
            else HedgeBudget(clock=clock)
        )
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.transfer_retries = max(0, int(transfer_retries))
        self.transfer_timeout_s = max(0.0, float(transfer_timeout_s))
        self.transfer_backoff_s = max(0.0, float(transfer_backoff_s))
        leg = str(transfer_leg or "").strip().lower()
        if leg and leg not in ("dma", "device", "wire", "host"):
            raise ValueError(
                f"transfer_leg must be dma|device|wire|host or empty, "
                f"got {transfer_leg!r}"
            )
        self.transfer_leg = leg
        self.source_timeout_s = max(0.0, float(source_timeout_s))
        self._sleep = sleep
        # Last published tier mode (gauge updates only on change).
        self._tier_mode_last: Optional[str] = None
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._metrics = metrics
        self._logger = logger
        self._rr = 0
        self._rr_lock = lockcheck.make_lock("ReplicaPool._rr_lock")
        # Guards replica-list MUTATION (scaler add/drain). Readers
        # iterate the current list object; mutators swap in a new list
        # atomically so routing never sees a half-edited one.
        self._replicas_lock = lockcheck.make_lock("ReplicaPool._replicas_lock")
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # Replicas whose synthetic probe was brownout-skipped LAST
        # sweep: the skip alternates, so probe cadence halves under a
        # brownout but restart-on-evidence still fires within two
        # sweeps (a live-advertising replica with a broken dataplane
        # must not hide behind its own burn storm forever).
        self._brownout_probe_skipped: set[int] = set()
        # Optional load-adaptive scaler (service/pool_scaler.py), set by
        # the config seam; started/stopped with the pool lifecycle.
        self.scaler: Optional[Any] = None
        # Lazy LoRA reconciliation: adapter name → load source (PEFT
        # dir or raw leaves dict). When a request names an adapter no
        # routable replica advertises, the pool asks one to load it
        # from here before giving up.
        self._adapter_sources: dict[str, Any] = {}
        self._refresh_primary()
        # Mid-stream failover: each replica offers the pool its
        # otherwise-terminal retryable requests (engine.try_handoff /
        # HTTPReplica stream loss → here → sibling.adopt). Prefill-tier
        # replicas additionally get the transfer exporter: finalized
        # prefills ship their KV blocks to a decode replica through
        # :meth:`_tier_transfer`.
        for replica in self._replicas:
            replica.set_handoff(self._make_handoff(replica))
            if replica.role == "prefill":
                replica.set_tier_exporter(self._make_tier_exporter(replica))
        self._publish_tier_mode()

    def _refresh_primary(self) -> None:
        self._primary_engine = next(
            (r.engine for r in self._replicas
             if isinstance(r, EngineReplica)),
            None,
        )

    # -- engine facade ----------------------------------------------------

    @property
    def family(self) -> str:
        eng = self._primary_engine
        return str(eng.family) if eng is not None else "llm"

    @property
    def model_name(self) -> str:
        eng = self._primary_engine
        if eng is not None:
            return str(eng.model_name)
        return self._replicas[0].name

    @property
    def tokenizer(self) -> Any:
        eng = self._primary_engine
        return eng.tokenizer if eng is not None else None

    @property
    def replicas(self) -> list[Replica]:
        return list(self._replicas)

    def __getattr__(self, name: str) -> Any:
        # Everything the pool does not reinterpret (lora_names,
        # max_prompt_tokens, embed, register_prefix, ...) delegates to
        # the primary in-proc engine — the pool is an ENGINE-shaped
        # object to its callers. (Only reached for attributes not
        # defined on the pool itself.)
        eng = self.__dict__.get("_primary_engine")
        if eng is not None and not name.startswith("__"):
            return getattr(eng, name)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self.start_sync()

    def start_sync(self) -> None:
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                replica.engine.start_sync()
        self.start_prober()
        if self.scaler is not None:
            self.scaler.start()

    async def stop(self, drain_s: float = 0.0) -> None:
        if self.scaler is not None:
            self.scaler.stop()
        self.stop_prober()
        for replica in self._replicas:
            # Detach the handoff (and tier exporter) FIRST: a pool-wide
            # shutdown must terminate in-flight work, not migrate it
            # replica to replica (re-decoding delivered prefixes and
            # emitting phantom failover metrics during a routine
            # deploy). A detached exporter makes prefill replicas
            # decode their last prefills locally — fused.
            replica.set_handoff(None)
            replica.set_tier_exporter(None)
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                replica.engine.stop_sync(drain_s)

    def close(self) -> None:
        if self.scaler is not None:
            self.scaler.stop()
        self.stop_prober()
        for replica in self._replicas:
            try:
                replica.close()
            except Exception as exc:  # noqa: BLE001 — close every replica regardless
                if self._logger is not None:
                    self._logger.errorf(
                        "replica %s close failed: %s", replica.name, exc
                    )

    # -- routing ----------------------------------------------------------

    def pick(
        self,
        exclude: Iterable[Replica] = (),
        *,
        require_stream: bool = False,
        adapter: str = "",
        prefer_roles: tuple = (),
    ) -> Replica:
        """Least-loaded routable replica: SERVING first, spill to
        DEGRADED, never RESTARTING/DOWN, probe-demoted, or draining.
        Round-robin rotation breaks load ties so equal replicas share
        traffic. ``require_stream`` restricts to stream-capable
        backends (in-proc engines and SSE-streaming remotes) — a
        unary-only HTTPReplica handed a streaming request would answer
        a 200 SSE with zero tokens, which is worse than an honest 502.
        ``adapter`` restricts to replicas ADVERTISING that LoRA adapter
        — routing a request where the weights aren't loaded would serve
        base-model output with a 200 (callers reconcile on miss:
        :meth:`_ensure_adapter`).

        ``prefer_roles`` narrows to tier roles when any candidate holds
        one (tiered routing sends fresh work to the prefill tier) and
        falls through to every candidate otherwise — a role tag must
        never turn a servable request into a 502.

        Weighted mode ranks by estimated completion time instead:
        ``(load + 1) / measured tokens/sec`` — the ROADMAP follow-up to
        queue-length routing; with no throughput signal anywhere it
        collapses to the same least-loaded pick."""
        excluded = {id(r) for r in exclude}
        replicas = self._replicas  # one snapshot: scaler swaps the list

        def routable(states: tuple[str, ...]) -> list[Replica]:
            return [
                r for r in replicas
                if id(r) not in excluded
                and not r.probe_failed
                and not r.draining
                and (r.supports_stream or not require_stream)
                and (not adapter or adapter in r.adapters())
                and r.state() in states
            ]

        candidates = routable(("SERVING",)) or routable(("DEGRADED",))
        if prefer_roles:
            # Tier routing is a PREFERENCE, never a partition: with the
            # preferred tier empty the pick falls through to whatever
            # still serves (the fused degradation rung), because a
            # request that could be served must never 502 over a role
            # tag.
            preferred = [r for r in candidates if r.role in prefer_roles]
            if preferred:
                candidates = preferred
        # SLO-compliance routing (the ROADMAP "route on slo_compliant"
        # item, closed by the brownout PR): replicas advertising
        # non-compliance — burn over budget, or brownout L3 — are
        # deprioritized with the same preference-never-partition
        # discipline as tier roles. None (no SLOs / unknown) counts as
        # compliant: absence of the signal must not starve a replica.
        if len(candidates) > 1:
            compliant = [
                r for r in candidates if r.slo_compliant() is not False
            ]
            if compliant and len(compliant) < len(candidates):
                candidates = compliant
        if candidates:
            with self._rr_lock:
                start = self._rr % len(candidates)
                self._rr += 1
            rotated = candidates[start:] + candidates[:start]
            if not self.weighted:
                return min(rotated, key=lambda r: r.load())
            return min(rotated, key=self._completion_score(rotated))
        raise ErrorNoHealthyReplica(
            f"{len(replicas)} replica(s), none "
            + ("stream-capable and " if require_stream else "")
            + (f"serving adapter {adapter!r} and " if adapter else "")
            + "SERVING or DEGRADED"
        )

    @staticmethod
    def _completion_score(
        candidates: Sequence[Replica],
    ) -> Callable[[Replica], float]:
        """Least-estimated-completion-time key: outstanding work (+1
        for the request being placed) over measured tokens/sec.
        Replicas without a signal (cold, unary HTTP) are assumed as
        fast as the FASTEST measured sibling — a cold replica is
        usually an idle one, and penalizing it would starve it of the
        traffic that would produce its first measurement. All-unknown
        → every rate equal → ordering identical to least-loaded."""
        rates = {id(r): max(0.0, r.throughput()) for r in candidates}
        known = [v for v in rates.values() if v > 0.0]
        default = max(known) if known else 1.0

        def score(r: Replica) -> float:
            rate = rates.get(id(r), 0.0) or default
            return (r.load() + 1) / rate

        return score

    def _submit_routed(
        self,
        prompt: Any,
        kw: dict,
        tried: list[Replica],
        *,
        require_stream: bool,
    ) -> tuple[Replica, Any]:
        """Submit with failover across replicas: per-replica overload or
        failure (429/5xx, open breaker) reroutes to the next candidate;
        request-shaped errors (400/413/...) raise immediately — they
        would fail identically everywhere. Adapter-bound requests route
        only to replicas advertising the adapter, lazily reconciling
        (asking a routable replica to load it) when none do."""
        adapter = str(kw.get("adapter") or "")
        # Disaggregated tiers: while both tiers are healthy, fresh work
        # lands on the prefill tier (the prefill replica ships KV blocks
        # to a decode replica after finalize); with either tier empty
        # the preference dissolves and any replica serves fused.
        # Adapter-bound requests route purely by adapter advertisement —
        # tier transfers exclude LoRA, so tier-routing them would just
        # pin adapter traffic to the prefill tier end-to-end.
        prefer: tuple = ()
        if not adapter and self.tier_mode == "tiered":
            prefer = ("prefill",)
        elif (
            not adapter
            and self.source_timeout_s > 0
            and self.tier_sources()
        ):
            # Pull-mode disaggregation: remote prefill SOURCES exist but
            # the pool itself is not tiered (no local prefill role).
            # Fresh work prefers local decode replicas, whose prefix
            # caches the source pull below warms before admission.
            prefer = ("decode",)
        last: Optional[BaseException] = None
        reconciled = False
        while True:
            try:
                replica = self.pick(
                    exclude=tried, require_stream=require_stream,
                    adapter=adapter, prefer_roles=prefer,
                )
            except ErrorNoHealthyReplica:
                if adapter and not reconciled:
                    # No routable replica has the adapter loaded: ask
                    # one to load it (registered source), or discover a
                    # remote that has it but was never probed.
                    reconciled = True
                    if self._ensure_adapter(adapter, tried):
                        continue
                if isinstance(last, ErrorTooManyRequests):
                    raise last from None  # keep the 429 + Retry-After
                if last is not None:
                    raise ErrorNoHealthyReplica(str(last)) from last
                if adapter and self._no_replica_has(adapter):
                    # Match the single-engine surface: an adapter nobody
                    # can serve (no weights anywhere, no registered
                    # source) is a REQUEST error, not an availability
                    # one.
                    from gofr_tpu.errors import ErrorInvalidParam

                    raise ErrorInvalidParam([
                        f"unknown LoRA adapter {adapter!r}; no replica "
                        f"has it loaded and no source is registered "
                        f"(pool.load_lora/register_adapter_source)"
                    ]) from None
                raise
            tried.append(replica)
            notes: list = []
            if (
                not adapter
                and isinstance(replica, EngineReplica)
                and self.source_timeout_s > 0
            ):
                try:
                    notes = self._source_prefill(replica, prompt, kw)
                except Exception as exc:  # noqa: BLE001 — the pull plane must never fail a submit
                    self._count_source("error")
                    if self._logger is not None:
                        self._logger.warnf(
                            "prefill-source pull errored (%s); serving "
                            "local-fused", exc,
                        )
            try:
                req = replica.submit(prompt, **kw)
                timeline = getattr(req, "timeline", None)
                if timeline is not None:
                    # note_transfer takes explicit timestamps, so the
                    # pull annotations (recorded before the request
                    # object existed) land on THIS request's trace.
                    for note in notes:
                        timeline.note_transfer(*note)
                return replica, req
            except Exception as exc:
                if not _is_reroutable(exc):
                    raise
                last = exc
                if self._logger is not None:
                    self._logger.warnf(
                        "replica %s rejected a submit (%s); rerouting",
                        replica.name, exc,
                    )

    def submit_generate(self, prompt: Any, **kw: Any) -> Any:
        """Route one generation. The returned handle's STREAM must work
        (callers can't say whether they will iterate it), so only
        stream-capable in-proc replicas qualify; unary-only HTTPReplicas
        serve through :meth:`generate_sync`/:meth:`generate` instead.
        Mid-stream replica loss is handled by the handoff path, not
        here."""
        _, req = self._submit_routed(prompt, kw, [], require_stream=True)
        return req

    # -- LoRA adapter reconciliation --------------------------------------

    def register_adapter_source(self, name: str, source: Any) -> None:
        """Record where adapter ``name`` loads from (PEFT checkpoint dir
        or raw leaves) WITHOUT loading it anywhere yet: the first
        request naming it triggers the lazy load on whichever replica
        the router would use."""
        self._adapter_sources[name] = source

    def load_lora(self, name: str, source: Any) -> int:
        """Engine-facade adapter load: registers the source for lazy
        sibling reconciliation and loads eagerly on ONE in-proc replica
        (the routing filter sends the adapter's traffic there; siblings
        pull the weights on demand — at failover or under load — rather
        than paying #replicas × load cost up front)."""
        self._adapter_sources[name] = source
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                return int(replica.engine.load_lora(name, source))
        raise RuntimeError(
            "no in-process replica to load a LoRA adapter into; remote "
            "replicas advertise their own adapter sets via health probes"
        )

    def unload_lora(self, name: str) -> None:
        """Unload ``name`` from every in-proc replica holding it and
        drop its lazy-load source (remote replicas manage their own
        adapter lifecycle; their advertisement refreshes on the next
        probe)."""
        self._adapter_sources.pop(name, None)
        found = False
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                try:
                    replica.engine.unload_lora(name)
                    found = True
                except KeyError:
                    continue
        if not found:
            raise KeyError(f"no loaded LoRA adapter {name!r}")

    def lora_names(self) -> list[str]:
        """Union of every replica's advertised adapter set plus the
        registered lazy sources — the pool-level OpenAI ``/v1/models``
        surface (a request may name any of these; routing/reconciliation
        places it)."""
        names: set[str] = set(self._adapter_sources)
        for replica in self._replicas:
            names.update(replica.adapters())
        return sorted(names)

    def _no_replica_has(self, adapter: str) -> bool:
        """True when the pool IS routable but no routable replica serves
        ``adapter`` — the request-shaped (400) case, distinct from an
        entirely-down pool (502)."""
        routable = [
            r for r in self._replicas
            if not r.probe_failed and not r.draining
            and r.state() in ("SERVING", "DEGRADED")
        ]
        return bool(routable) and all(
            adapter not in r.adapters() for r in routable
        )

    def _ensure_adapter(
        self, adapter: str, exclude: list[Replica]
    ) -> bool:
        """Lazy reconciliation: make SOME routable replica serve
        ``adapter``. First refresh unprobed remotes (a remote may have
        the adapter loaded without this pool ever having asked), then
        ask replicas to load it from the registered source. True when a
        subsequent :meth:`pick` can succeed."""
        excluded = {id(r) for r in exclude}
        candidates = [
            r for r in self._replicas
            if id(r) not in excluded
            and not r.probe_failed and not r.draining
            and r.state() in ("SERVING", "DEGRADED")
        ]
        # Discovery pass: remotes advertise adapter sets via probes; an
        # un-probed or stale remote may already have the weights. This
        # runs INSIDE the submit path, so the budget is a short
        # discovery one, not the prober thread's full probe_timeout_s —
        # several slow remotes must not stack 30s each onto a request.
        discovery_timeout_s = min(self.probe_timeout_s, 5.0)
        for replica in candidates:
            if replica.remote and adapter not in replica.adapters():
                try:
                    replica.probe(discovery_timeout_s)
                except Exception:  # noqa: BLE001 — discovery is best-effort
                    continue
        if any(adapter in r.adapters() for r in candidates):
            return True
        source = self._adapter_sources.get(adapter)
        if source is None:
            return False
        for replica in candidates:
            if replica.load_adapter(adapter, source):
                if self._logger is not None:
                    self._logger.infof(
                        "adapter %r reconciled onto replica %s (lazy "
                        "load)", adapter, replica.name,
                    )
                return True
        return False

    # -- unary with hedged retries ---------------------------------------

    def _hedge_delay(self, deadline: Optional[Deadline]) -> float:
        """Jittered hedge trigger, clamped under the caller's deadline."""
        delay = self.hedge_delay_s * (0.75 + 0.5 * self._rng.random())
        if deadline is not None:
            delay = min(delay, max(deadline.remaining(), 0.0))
        return delay

    def _hedge_eligible(self, deadline: Optional[Deadline]) -> bool:
        """Non-consuming eligibility twin of :meth:`should_hedge`:
        deadline still live and budget available. Shared by the
        brownout suppress-hedge counter so 'what we suppressed' can
        never drift from 'what would have fired'."""
        if deadline is not None and deadline.remaining() <= 0:
            return False
        return self.hedge_budget.available() >= 1.0

    def should_hedge(self, deadline: Optional[Deadline]) -> bool:
        """Deadline-aware, budgeted second-attempt decision (latency
        hedges AND fast-fail retries): never hedge work whose deadline
        already passed, and never without budget — an exhausted bucket
        means the tier is slow EVERYWHERE and doubling load would dig
        the hole deeper."""
        if not self._hedge_eligible(deadline):
            return False
        return self.hedge_budget.try_acquire()

    def _count_hedge(self, kind: str, kw: Optional[dict] = None) -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_hedged_requests_total", "kind", kind
            )
        # Trace annotation: the hedge/retry hop lands in the request's
        # trace (instant span under the caller's traceparent). No-op
        # without an active exporter.
        from gofr_tpu.serving.observability import emit_instant_span

        emit_instant_span(
            "tpu.hedge",
            (kw or {}).get("traceparent"),
            {"kind": kind},
        )

    def generate_sync(
        self, prompt: Any, timeout: float = 300.0, **kw: Any
    ) -> Any:
        """Unary generation with bounded hedged retries: a slow primary
        is raced by one budgeted hedge on a different replica (first
        success wins, the loser is cancelled); a fast-failing primary is
        retried once on a sibling. Composes with per-replica circuit
        breakers — their fast-fails are reroute signals here."""
        deadline = kw.get("deadline")
        tried: list[Replica] = []
        _, req = self._submit_routed(prompt, kw, tried, require_stream=False)
        live = [req]
        primary_exc: Optional[BaseException] = None
        try:
            return req.future.result(timeout=self._hedge_delay(deadline))
        except cf.TimeoutError:
            pass  # primary slow → consider a latency hedge below
        except cf.CancelledError:
            live, primary_exc = [], ErrorNoHealthyReplica("request cancelled")
        except Exception as exc:
            if not _is_reroutable(exc):
                raise
            live, primary_exc = [], exc  # primary failed fast → retry
        # Hedges AND fast-fail retries both spend from the SAME bucket:
        # under tier-wide overload, unbudgeted retries would double load
        # exactly when every replica is already failing. The sibling
        # check comes FIRST (short-circuit) so a pool with no routable
        # second replica never burns tokens it cannot use — draining the
        # bucket on impossible hedges would starve real ones the moment
        # a sibling recovers. A browned-out primary (L1+) suppresses the
        # LATENCY hedge — its slowness is managed degradation, and a
        # duplicate would land the exact optional load the brownout is
        # shedding on a sibling that is likely storming too. Fast-fail
        # retries (primary_exc set) still reroute: the request NEEDS a
        # server.
        if (
            self._routable_sibling_exists(
                tried, adapter=str(kw.get("adapter") or "")
            )
            and not (
                primary_exc is None
                and self._hedge_suppressed(tried, deadline)
            )
            and self.should_hedge(deadline)
        ):
            try:
                _, second = self._submit_routed(
                    prompt, kw, tried, require_stream=False
                )
            except Exception as exc:  # noqa: BLE001 — ride the primary if no sibling
                if not live:
                    raise (primary_exc or exc)
            else:
                live.append(second)
                self._count_hedge(
                    "retry" if primary_exc is not None else "hedge", kw
                )
        elif not live:
            # Primary failed with no budgeted/routable second attempt:
            # fail honestly rather than amplify the overload.
            assert primary_exc is not None
            raise primary_exc
        return self._first_result(live, timeout, primary_exc)

    def _note_brownout_action(self, replica: Replica, action: str) -> None:
        """Count a pool-side ladder action. Routed through the in-proc
        engine's controller when reachable so the Prometheus counter
        AND /debug/brownout's per-action table agree; remote replicas
        (level-only advertisement, no controller here) count straight
        to the metric."""
        bc = getattr(getattr(replica, "engine", None), "_brownout", None)
        if bc is not None:
            bc.note_action(action)
            return
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_brownout_actions_total",
                "model", self.model_name, "action", action,
            )

    def _hedge_suppressed(
        self, tried: list[Replica], deadline: Optional[Deadline] = None
    ) -> bool:
        """True when the primary replica advertises brownout L1+ —
        hedging against managed degradation is the optional load the
        ladder exists to shed (serving/brownout.py). The action counter
        only increments when a hedge was otherwise ELIGIBLE (live
        deadline, budget available): counting every slow request under
        a storm would overstate what the ladder actually suppressed."""
        primary = tried[0] if tried else None
        if primary is None:
            return False
        level = primary.brownout_level()
        if level is None or level < 1:
            return False
        if self._hedge_eligible(deadline):
            self._note_brownout_action(primary, "suppress_hedge")
        return True

    def _routable_sibling_exists(
        self, tried: list[Replica], adapter: str = ""
    ) -> bool:
        excluded = {id(r) for r in tried}
        return any(
            id(r) not in excluded
            and not r.probe_failed
            and not r.draining
            and (not adapter or adapter in r.adapters())
            and r.state() in ("SERVING", "DEGRADED")
            for r in self._replicas
        )

    def _first_result(
        self,
        reqs: list[Any],
        timeout: float,
        last_exc: Optional[BaseException],
    ) -> Any:
        """First successful attempt wins; losers are cancelled so no
        replica decodes for a caller that already has its answer."""
        end = time.monotonic() + timeout
        pending = list(reqs)
        while pending:
            by_future = {r.future: r for r in pending}
            done, _ = cf.wait(
                list(by_future),
                timeout=max(0.0, end - time.monotonic()),
                return_when=cf.FIRST_COMPLETED,
            )
            if not done:
                for r in pending:
                    r.cancel_request()
                raise ErrorDeadlineExceeded(
                    f"no replica answered within {timeout:.1f}s"
                )
            for future in done:
                pending.remove(by_future[future])
                try:
                    result = future.result()
                except Exception as exc:  # noqa: BLE001 — keep racing the others
                    last_exc = exc
                    continue
                for loser in pending:
                    loser.cancel_request()
                return result
        raise last_exc if last_exc is not None else ErrorNoHealthyReplica()

    async def generate(self, prompt: Any, **kw: Any) -> Any:
        import asyncio
        from functools import partial

        return await asyncio.get_running_loop().run_in_executor(
            None, partial(self.generate_sync, prompt, **kw)
        )

    async def generate_stream(
        self, prompt: Any, **kw: Any
    ) -> Any:
        """Async iterator over token ids (engine-API parity); replica
        loss mid-stream is healed by the handoff path underneath."""
        import asyncio

        req = self.submit_generate(prompt, **kw)
        loop = asyncio.get_running_loop()
        while True:
            tok = await loop.run_in_executor(None, req.stream.get)
            if tok is None:
                return
            yield tok

    # -- mid-stream failover (engine handoff target) ----------------------

    def _make_handoff(self, source: EngineReplica) -> Callable[[Any], bool]:
        def handoff(req: Any) -> bool:
            return self._failover(req, source)

        return handoff

    def _failover(self, req: Any, source: Replica) -> bool:
        """Adopt a salvaged request from a dying replica onto a healthy
        sibling. True = requeued (stream/future intact); False = the
        caller fails it through its terminal path. Adapter-bound
        requests only land on siblings advertising the adapter — with
        lazy reconciliation when none does, same as fresh submits."""
        tried: list[Replica] = [source]
        reconciled = False
        for _ in range(len(self._replicas) + 1):
            try:
                # Adoption continues a live STREAM handle: in-proc
                # replicas requeue_replay it; streaming remotes re-open
                # the continuation over SSE (greedy only).
                replica = self.pick(
                    exclude=tried, require_stream=True,
                    adapter=req.adapter,
                )
            except ErrorNoHealthyReplica:
                if req.adapter and not reconciled:
                    reconciled = True
                    if self._ensure_adapter(req.adapter, tried):
                        continue
                return False
            tried.append(replica)
            if not replica.adopt(req):
                continue
            timeline = getattr(req, "timeline", None)
            if timeline is not None:
                # Rides the request's lifecycle timeline: the failover
                # hop shows up in /debug/flight and as a span in the
                # request's ONE trace (emitted at retirement on the
                # adopting replica).
                timeline.note_failover(
                    source.name, replica.name, timeline.hub.now()
                )
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_tpu_failovers_total",
                    "from", source.name, "to", replica.name,
                )
                if source.remote:
                    # A REMOTE stream died mid-SSE and resumed on a
                    # sibling — the multi-host data plane's signature
                    # event, counted separately from in-proc failovers.
                    self._metrics.increment_counter(
                        "app_tpu_remote_stream_failovers_total",
                        "from", source.name, "to", replica.name,
                    )
            if self._logger is not None:
                self._logger.infof(
                    "failover: request moved %s → %s (%d token(s) already "
                    "delivered)",
                    source.name, replica.name, len(req.token_ids),
                )
            return True
        return False

    # -- disaggregated prefill/decode tier --------------------------------

    def _compute_tier_mode(self) -> str:
        """``"tiered"`` while BOTH tiers have a routable replica,
        ``"fused"`` otherwise (including pools with no roles at all).
        Fused means role tags stop steering routing and every replica
        serves both phases — draining the last prefill replica degrades
        the pool to exactly the pre-tier behavior, with requests still
        served."""
        replicas = self._replicas  # one snapshot

        def healthy(role: str) -> bool:
            return any(
                r.role == role
                and not r.probe_failed
                and not r.draining
                # A replica that cannot do its tier's HALF of the
                # transfer (remote, until the payload grows a wire
                # form) must not flip the pool tiered: an
                # import-incapable decode target makes every transfer
                # a guaranteed-futile retry loop, and an
                # export-incapable prefill replica would pin fresh
                # traffic to fused serving while the real decode tier
                # idles. Either still serves as an ordinary routable
                # replica.
                and (role != "decode" or r.supports_tier_import)
                and (role != "prefill" or r.supports_tier_export)
                and r.state() in ("SERVING", "DEGRADED")
                for r in replicas
            )

        if not any(r.role != "fused" for r in replicas):
            return "fused"
        return "tiered" if healthy("prefill") and healthy("decode") else (
            "fused"
        )

    @property
    def tier_mode(self) -> str:
        mode = self._compute_tier_mode()
        self._publish_tier_mode(mode)
        return mode

    def _publish_tier_mode(self, mode: Optional[str] = None) -> None:
        """``app_tpu_tier_mode`` (1 = tiered, 0 = fused), published on
        change only — every submit consults the mode, and a gauge write
        per request would be noise."""
        if mode is None:
            mode = self._compute_tier_mode()
        if mode == self._tier_mode_last:
            return
        self._tier_mode_last = mode
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_tpu_tier_mode", 1.0 if mode == "tiered" else 0.0
            )
        if self._logger is not None:
            self._logger.infof(
                "replica pool tier mode → %s", mode,
            )

    def _make_tier_exporter(
        self, source: Replica
    ) -> Callable[[Any, Any], bool]:
        def exporter(req: Any, payload: Any) -> bool:
            return self._tier_transfer(req, payload, source)

        return exporter

    def _pick_tier_target(
        self,
        exclude: Iterable[Replica],
        leg_for: Optional[Callable[[Replica], Optional[str]]] = None,
    ) -> Optional[Replica]:
        """A routable decode-tier replica for a block transfer, or None
        (the caller then falls back through the degradation ladder).
        Same weighted/least-loaded ranking as :meth:`pick`, restricted
        to decode-role stream-capable replicas; ``leg_for`` additionally
        filters to targets some still-permitted transfer leg can reach
        (a wire-pinned pool must not pick an in-proc sibling it can
        never ship to)."""
        excluded = {id(r) for r in exclude}
        candidates = [
            r for r in self._replicas
            if r.role == "decode"
            and id(r) not in excluded
            and not r.probe_failed
            and not r.draining
            and r.supports_stream
            and r.supports_tier_import
            and r.state() in ("SERVING", "DEGRADED")
            and (leg_for is None or leg_for(r) is not None)
        ]
        if not candidates:
            return None
        if not self.weighted:
            return min(candidates, key=lambda r: r.load())
        return min(candidates, key=self._completion_score(candidates))

    def _transfer_delay(self, attempt: int) -> float:
        """Jittered exponential backoff between transfer attempts —
        uncoordinated retries, so a fleet of prefill replicas hitting
        one rejecting decode replica cannot re-spike it in lockstep
        (graftlint GL013: every I/O retry loop backs off)."""
        base = self.transfer_backoff_s * (2 ** attempt)
        return base * (0.5 + self._rng.random())

    def _count_transfer(self, result: str, leg: str = "none") -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_tier_transfers_total",
                "result", result, "leg", leg or "none",
            )

    def _transfer_leg_for(
        self, target: Replica, banned: "set[str]"
    ) -> Optional[str]:
        """The best transfer leg this target can serve, honoring the
        ``TPU_TRANSFER_LEG`` pin and the legs already ``banned`` by a
        failure during this transfer — the per-target half of the
        dma → device/wire → host-bounce ladder. None = unreachable
        (the pool picks another target or falls to the fused rungs).

        The ``dma`` rung tops the ladder for REMOTE targets that
        advertise the handle protocol: control (a tiny claim-ticket
        POST) and data (a direct transfer-server fetch) travel
        separate paths, so the ops-port POST stops scaling with the
        payload. In-proc targets get dma only under an explicit pin —
        the device leg is strictly better inside one process, and the
        automatic ladder must not regress it to a loopback socket."""
        order: "tuple[str, ...]" = (
            (self.transfer_leg,) if self.transfer_leg
            else ("dma", "device", "wire", "host")
        )
        for leg in order:
            if leg in banned:
                continue
            if leg == "dma":
                if target.remote and getattr(
                    target, "supports_dma_import", False
                ):
                    return leg
                if (
                    not target.remote
                    and self.transfer_leg == "dma"
                    and getattr(target, "supports_dma_import", False)
                ):
                    return leg  # pinned loopback (CI/bench single-process)
            elif leg == "device":
                if not target.remote and getattr(
                    target, "supports_device_import", False
                ):
                    return leg
            elif leg == "wire":
                if target.remote and target.supports_tier_import:
                    return leg
            elif not target.remote:
                return leg  # host bounce: any in-proc importer
        return None

    def _tier_transfer(
        self, req: Any, payload_src: Any, source: Replica
    ) -> bool:
        """Ship a finalized prefill (request + KV-block payload) to a
        decode replica. ``payload_src`` is the payload or a zero-arg
        factory for it — the exporting scheduler defers the expensive
        device→host extraction behind this method's cheap gates, so a
        hop-capped request or a collapsed decode tier never pays the
        host bounce. Robustness-first: the attempt loop carries the
        request's own ``Deadline``/``CancelToken`` plus a transfer-wide
        wall-clock bound (``TPU_TRANSFER_TIMEOUT_S``) and a jittered-
        backoff retry budget (``TPU_TRANSFER_RETRIES``); every exit is
        a rung of the degradation ladder, never a dropped request.

        **Leg selection** (the perf half of the ladder): per target the
        pool ships over the best leg it can serve — ``device``
        (in-proc paged sibling on the shared JAX runtime: per-block
        device extraction + shard-to-shard placement, zero host
        copies), ``wire`` (remote decode replica with an ops-port
        import service: length-prefixed POST of the host-bounced
        payload), or ``host`` (the PR 8 host bounce). A leg that FAILS
        mid-transfer is banned for the rest of this transfer and the
        same target retries one rung down — any leg failure degrades to
        the next rung, terminally to fused serving, byte-identically
        and under ONE trace id. ``TPU_TRANSFER_LEG`` pins a single leg
        (operators bisecting a transfer problem); payload extraction is
        lazy PER LEG, so a device-leg transfer never pays the host pull
        and a collapsed decode tier pays neither.

        1. a decode replica imports the blocks → ``result="ok"``
           (zero-copy decode) or ``"fused"`` (it rejected the payload
           but adopted the request → re-prefills there);
        2. retries exhausted / transfer deadline with the decode tier
           still nominally present → the request requeues WITHOUT
           blocks on any stream-capable sibling via the ordinary
           failover path → ``result="failed_over"`` (fused re-prefill
           elsewhere);
        3. no routable decode target at all (tier collapsed before
           anything was tried), the hop cap, or nothing adopting it →
           False: the PREFILL replica decodes it locally
           (``result="local_fused"``) — its slot and blocks are still
           live, so this rung costs nothing and can never fail.

        A request whose deadline expired or whose caller cancelled
        mid-transfer is released to the scheduler's reap instead of
        being shipped (``result="expired"``) — transferring work nobody
        will wait for helps no one.

        The backoff sleeps run on the exporting scheduler thread
        (bounded by the transfer deadline and taken only on FAILING
        attempts), so a flaky decode tier briefly slows that replica's
        other prefills rather than silently doubling its work."""
        if req.tier_hops >= 2 or self._compute_tier_mode() != "tiered":
            # Hop cap (settle into fused serving rather than ping-pong
            # between a prefill tier and a rejecting decode tier), or
            # the decode tier already collapsed — decode locally with
            # the blocks that are still live in this replica's slot
            # instead of paying a sibling re-prefill.
            self._count_transfer("local_fused")
            return False
        req.tier_hops += 1
        if req.cancel.cancelled or req.future.cancelled() or (
            req.deadline is not None and req.deadline.expired()
        ):
            # Dead before the expensive leg: never pay the device→host
            # extraction for work nobody will consume — the source's
            # reap retires it within one window.
            self._count_transfer("expired")
            return False
        # The clock starts BEFORE extraction: the histogram's meaning
        # is extract→import, and on the host leg the device→host pull
        # is routinely the dominant part.
        start = self._clock()
        # Lazy PER-LEG payload materialization, memoized across
        # attempts: the wire leg ships the host-bounced form, so it
        # shares the "host" entry; a device-pinned transfer never pulls
        # a plane to host at all.
        payloads: dict[str, Any] = {}

        def memo_key(leg: str) -> str:
            return leg if leg in ("device", "dma") else "host"

        def payload_for(leg: str) -> Any:
            if leg == "dma":
                # The dma leg stages the HOST form on this process's
                # transfer server and ships only the claim ticket. The
                # host bytes memoize under their own key, so a dma →
                # wire descent re-ships the same extraction without a
                # second device pull; a staging failure (the
                # transfer.dma.offer fault, server down) raises out to
                # the attempt loop, which bans the rung.
                if "dma" not in payloads:
                    host = payload_for("host")
                    if host is None:
                        payloads["dma"] = None
                    else:
                        from gofr_tpu.service.dma import (
                            get_transfer_server,
                        )

                        payloads["dma"] = get_transfer_server().offer(
                            host, src=source.name
                        )
                return payloads["dma"]
            key = memo_key(leg)
            if key not in payloads:
                if callable(payload_src):
                    try:
                        payloads[key] = payload_src(key)
                    except TypeError:
                        # Legacy zero-arg factories (host form only).
                        payloads[key] = payload_src()
                else:
                    payloads[key] = payload_src
            return payloads[key]

        banned: set[str] = set()

        def leg_for(target: Replica) -> Optional[str]:
            return self._transfer_leg_for(target, banned)

        bound = Deadline.after(self.transfer_timeout_s, clock=self._clock)
        tried: list[Replica] = []
        result = "abandoned"
        last_exc: Optional[BaseException] = None
        for attempt in range(self.transfer_retries + 1):
            if req.cancel.cancelled or req.future.cancelled() or (
                req.deadline is not None and req.deadline.expired()
            ):
                self._count_transfer("expired")
                return False  # the source's reap retires it within one window
            if bound.expired():
                result = "timeout"
                break
            verdict: Optional[str] = None
            target: Optional[Replica] = None
            leg = ""
            try:
                # Fault seam: the transfer leg itself dying (prefill
                # replica lost mid-ship, serialization fault).
                faults.fire(
                    "tier.transfer", request=req, source=source.name,
                    attempt=attempt,
                )
                target = self._pick_tier_target([source, *tried], leg_for)
                if target is None:
                    result = "no_target"
                    break
                leg = leg_for(target) or "host"
                # Excluded from later attempts whether the import
                # returns None OR raises — re-picking the same broken
                # replica would skip its healthy siblings. (A LEG
                # failure un-excludes it below: the rung broke, not
                # the replica.)
                tried.append(target)
                verdict = target.import_prefilled(req, payload_for(leg))
            except Exception as exc:  # noqa: BLE001 — every attempt failure is retried or degraded
                last_exc = exc
                verdict = None
                if leg and leg != "host" and not self.transfer_leg:
                    # The LEG failed (extraction, serialization, a
                    # device_put across meshes, the import itself):
                    # ban it for this transfer and let the SAME target
                    # retry one rung down — device → wire → host-
                    # bounce → (below) fused.
                    banned.add(leg)
                    if target is not None and tried and (
                        tried[-1] is target
                    ):
                        tried.pop()
                    if self._logger is not None:
                        self._logger.warnf(
                            "tier transfer %s leg failed (%s); "
                            "degrading to the next rung", leg, exc,
                        )
            if verdict:
                assert target is not None
                duration = self._clock() - start
                outcome = "ok" if verdict == "imported" else "fused"
                self._count_transfer(outcome, leg)
                if self._metrics is not None:
                    self._metrics.record_histogram(
                        "app_tpu_tier_transfer_seconds", duration
                    )
                    payload = payloads.get(memo_key(leg))
                    nbytes = getattr(payload, "nbytes", None)
                    if outcome == "ok" and callable(nbytes):
                        self._metrics.add_counter(
                            "app_tpu_tier_transfer_bytes_total",
                            float(nbytes()), "leg", leg,
                        )
                timeline = getattr(req, "timeline", None)
                if timeline is not None:
                    timeline.note_transfer(
                        source.name, target.name, start, self._clock(),
                        outcome, leg,
                    )
                if self._logger is not None:
                    payload = payloads.get(memo_key(leg))
                    self._logger.infof(
                        "tier transfer %s → %s [%s]: %s (%d block(s), "
                        "attempt %d)",
                        source.name, target.name, leg, outcome,
                        payload.n_blocks if payload is not None else 0,
                        attempt + 1,
                    )
                return True
            if attempt < self.transfer_retries:
                self._sleep(self._transfer_delay(attempt))
        if self._logger is not None:
            self._logger.warnf(
                "tier transfer from %s abandoned (%s%s); falling back "
                "to fused serving",
                source.name, result,
                f": {last_exc}" if last_exc is not None else "",
            )
        if result == "no_target" and not tried:
            # The decode tier vanished mid-transfer (nothing was even
            # tried): the prefill replica's slot still holds the
            # finished blocks, so local decode is strictly cheaper than
            # a sibling re-prefill. With targets TRIED and rejecting,
            # fall through to the failover rung instead — re-prefilling
            # on the (live, merely import-rejecting) decode tier keeps
            # the decode windows off this prefill replica.
            self._count_transfer("local_fused")
            return False
        # Retries/deadline exhausted against a PRESENT-but-rejecting
        # decode tier: requeue WITHOUT the payload through the ordinary
        # failover path (decode siblings included) — the fused fallback
        # rung. Byte-identical output either way: the adopting replica
        # re-prefills the same prompt under the same seed.
        if req.retryable() and self._failover(req, source):
            self._count_transfer("failed_over")
            timeline = getattr(req, "timeline", None)
            if timeline is not None:
                timeline.note_transfer(
                    source.name, "", start, self._clock(), "failed_over",
                    "none",
                )
            return True
        self._count_transfer("local_fused")
        return False

    # -- remote prefill sources (the multi-host pull plane) ---------------

    def tier_sources(self) -> "list[Replica]":
        """Routable remote prefill-role replicas that can be PULLED
        from (``/ops/tier-export``): the reverse of the push-transfer
        plane — here the LOCAL decode engine asks a remote prefill pod
        for blocks it already computed, so independently scaled prefill
        and decode fleets across hosts share work without a shared
        process or a shared JAX runtime."""
        return [
            r for r in self._replicas
            if r.remote
            and r.role == "prefill"
            and getattr(r, "supports_tier_source", False)
            and not r.probe_failed
            and not r.draining
            and r.state() in ("SERVING", "DEGRADED")
        ]

    def _count_source(self, kind: str) -> None:
        """``app_tpu_tier_sources_total{kind}``: hit / miss / rejected /
        error / expired — the pull plane's outcome counter (the push
        plane's twin of ``app_tpu_tier_transfers_total``)."""
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_tier_sources_total", "kind", kind,
            )

    def _source_prefill(
        self, replica: Replica, prompt: Any, kw: dict
    ) -> "list[tuple[str, str, float, float, str, str]]":
        """Before admitting a FRESH request on in-proc ``replica``, try
        to warm its prefix cache with blocks pulled from a remote
        prefill source. Per source the pull descends its own two-rung
        ladder — a ``dma`` claim ticket (tiny control reply + direct
        transfer-server fetch) first, the inline ``wire`` body on any
        dma failure — and EVERY failure mode ends at the same terminal
        rung: the request prefills locally, byte-identical, zero 5xx.
        Returns timeline annotations ``(src, dst, start, end, result,
        leg)`` the caller attaches to the request once it exists, so
        the whole descent shows on ONE trace's ``/debug/flight``
        record."""
        notes: "list[tuple[str, str, float, float, str, str]]" = []
        if self.source_timeout_s <= 0:
            return notes
        sources = self.tier_sources()
        if not sources:
            return notes
        engine = getattr(replica, "engine", None)
        if engine is None or not getattr(engine, "kv_block", 0):
            return notes
        B = int(engine.kv_block)
        if isinstance(prompt, str):
            tok = getattr(engine, "tokenizer", None)
            if tok is None:
                return notes
            try:
                ids = [int(t) for t in tok.encode(prompt)]
            except Exception:  # noqa: BLE001 — the submit itself will surface a tokenize error
                return notes
        else:
            try:
                ids = [int(t) for t in prompt]
            except (TypeError, ValueError):
                return notes
        if len(ids) < B:
            return notes  # shorter than one block: nothing to pull
        radix = getattr(engine, "_radix", None)
        if radix is not None and radix.peek(ids) >= (len(ids) // B) * B:
            # Everything cacheable is already warm locally (peek is the
            # non-mutating probe): a pull would ship bytes the import
            # will skip anyway.
            return notes
        deadline = kw.get("deadline")
        budget = Deadline.after(self.source_timeout_s, clock=self._clock)
        traceparent = kw.get("traceparent")
        from gofr_tpu.ops.kv_cache import KVHandlePayload
        from gofr_tpu.service.dma import dma_fetch

        for source in sources:
            modes = (
                ("dma", "wire")
                if getattr(source, "_tier_dma", False) else ("wire",)
            )
            for mode in modes:
                if budget.expired() or (
                    deadline is not None and deadline.expired()
                ):
                    self._count_source("expired")
                    return notes
                start = self._clock()
                try:
                    # Fault seam: the source dying between discovery
                    # and pull.
                    faults.fire(
                        "transfer.source.pull", source=source.name,
                        mode=mode,
                    )
                    payload = source.fetch_prefilled(
                        ids, deadline=budget,
                        timeout_s=self.source_timeout_s,
                        traceparent=traceparent, mode=mode,
                    )
                    if isinstance(payload, KVHandlePayload):
                        payload = dma_fetch(payload, deadline=budget)
                except Exception as exc:  # noqa: BLE001 — every pull failure degrades to local prefill
                    self._count_source("error")
                    notes.append((
                        source.name, replica.name, start, self._clock(),
                        "source_error", mode,
                    ))
                    if self._logger is not None:
                        self._logger.warnf(
                            "prefill-source pull from %s [%s] failed "
                            "(%s); degrading one rung",
                            source.name, mode, exc,
                        )
                    if getattr(exc, "kind", "") == "connect":
                        break  # the source is GONE: next source, not next rung
                    continue  # one rung down: the inline wire body
                end = self._clock()
                if payload is None:
                    self._count_source("miss")
                    notes.append((
                        source.name, replica.name, start, end,
                        "source_miss", mode,
                    ))
                    break  # an authoritative miss: re-asking via wire cannot hit
                # Bounded wait for the APPLY (never past the budget):
                # the submit that follows must deterministically
                # admission-alias the warm blocks.
                verdict = engine.import_payload(
                    payload,
                    wait_s=max(0.0, min(1.0, budget.remaining())),
                )
                if verdict == "imported":
                    self._count_source("hit")
                    notes.append((
                        source.name, replica.name, start, self._clock(),
                        "source_hit", mode,
                    ))
                    if self._metrics is not None:
                        nbytes = getattr(payload, "nbytes", None)
                        if callable(nbytes):
                            self._metrics.add_counter(
                                "app_tpu_tier_transfer_bytes_total",
                                float(nbytes()), "leg", mode,
                            )
                    return notes
                # Geometry drift / corrupt body: the wire rung would
                # reject identically, so stop descending — local
                # prefill is the rung below.
                self._count_source("rejected")
                notes.append((
                    source.name, replica.name, start, self._clock(),
                    "source_rejected", mode,
                ))
                return notes
        return notes

    # -- membership (scaler spawn/drain) ----------------------------------

    def add_replica(self, replica: Replica) -> Replica:
        """Admit a new replica into routing: wire the failover handoff,
        publish its state gauge, and (for in-proc engines) start it if
        the factory did not. The list swap is atomic so concurrent
        picks see either the old or the new membership, never a
        half-edited one."""
        if isinstance(replica, EngineReplica):
            eng = replica.engine
            if not getattr(eng, "_running", True):
                eng.start_sync()
        replica.set_handoff(self._make_handoff(replica))
        if replica.role == "prefill":
            replica.set_tier_exporter(self._make_tier_exporter(replica))
        with self._replicas_lock:
            self._replicas = [*self._replicas, replica]
            self._refresh_primary()
        self._publish_state(replica)
        self.publish_pool_gauges()
        self._publish_tier_mode()
        if self._logger is not None:
            self._logger.infof(
                "replica %s joined the pool (%d total)", replica.name,
                len(self._replicas),
            )
        return replica

    def drain_replica(
        self,
        replica: Replica,
        *,
        timeout_s: float = 30.0,
        poll_s: float = 0.05,
        sleep: Callable[[float], None] = time.sleep,
    ) -> bool:
        """Retire a replica WITHOUT dropping work: stop routing to it
        immediately (``draining``), wait (bounded) for its in-flight
        requests to complete, then close and remove it. If load has not
        reached zero by ``timeout_s`` the drain ABORTS — the replica
        re-enters routing and nothing in flight is dropped; the caller
        (scaler sweep, operator) simply retries later."""
        if replica not in self._replicas:
            return False
        replica.draining = True
        self.publish_pool_gauges()
        # Draining the last replica of a tier flips the pool to fused
        # serving NOW — routing must not keep preferring a tier that
        # can no longer complete its half.
        self._publish_tier_mode()
        deadline = self._clock() + max(0.0, float(timeout_s))
        while replica.load() > 0:
            if self._clock() >= deadline:
                replica.draining = False
                self.publish_pool_gauges()
                if self._logger is not None:
                    self._logger.warnf(
                        "drain of replica %s aborted: %d request(s) "
                        "still in flight after %.1fs; re-admitted to "
                        "routing", replica.name, replica.load(), timeout_s,
                    )
                return False
            sleep(poll_s)
        replica.set_handoff(None)
        replica.set_tier_exporter(None)
        with self._replicas_lock:
            self._replicas = [r for r in self._replicas if r is not replica]
            self._refresh_primary()
        self._publish_tier_mode()
        try:
            replica.close()
        except Exception as exc:  # noqa: BLE001 — the replica already left routing
            if self._logger is not None:
                self._logger.errorf(
                    "retired replica %s close failed: %s", replica.name, exc
                )
        self.publish_pool_gauges()
        if self._logger is not None:
            self._logger.infof(
                "replica %s drained and retired (%d remain)", replica.name,
                len(self._replicas),
            )
        return True

    def publish_pool_gauges(self) -> None:
        """``app_tpu_pool_replicas{state=…}``: pool composition by
        routing state (draining counted as its own state — those
        replicas still finish work but take no new requests)."""
        if self._metrics is None:
            return
        counts = {
            "serving": 0, "degraded": 0, "restarting": 0, "down": 0,
            "draining": 0,
        }
        for r in self._replicas:
            if r.draining:
                counts["draining"] += 1
            elif r.probe_failed:
                counts["down"] += 1
            else:
                counts[r.state().lower()] = (
                    counts.get(r.state().lower(), 0) + 1
                )
        for state, n in counts.items():
            self._metrics.set_gauge(
                "app_tpu_pool_replicas", float(n), "state", state
            )

    # -- active probing ---------------------------------------------------

    def probe_once(self) -> dict[str, str]:
        """One synthetic-probe sweep (the prober thread's body; tests
        call it directly — no thread, no sleeps). Per replica:

        * RESTARTING — its supervisor is mid-recovery; leave it alone.
        * DOWN — demote, attempt a revive, then probe; only a PASSING
          probe re-admits it.
        * SERVING/DEGRADED — probe; a failure demotes it and requests a
          supervisor restart (restart on evidence, not just on crash).
        """
        results: dict[str, str] = {}
        skipped_last = self._brownout_probe_skipped
        self._brownout_probe_skipped = set()
        for replica in self._replicas:
            state = replica.state()
            if state == "RESTARTING":
                results[replica.name] = "restarting"
            elif state == "DOWN":
                # Probation: only a PASSING probe re-admits a revived
                # replica (a merely-busy one stays out until it proves
                # the dataplane end to end).
                replica.probe_failed = True
                results[replica.name] = (
                    self._probe_replica(replica)
                    if replica.revive(self.probe_timeout_s) else "down"
                )
            elif (
                not replica.probe_failed
                and not replica.remote
                and id(replica) not in skipped_last
                and (replica.brownout_level() or 0) >= 1
            ):
                # Brownout L1 sheds optional work, and an IN-PROC
                # synthetic probe is a whole greedy generation through
                # the dataplane. A routable local replica advertising
                # L1+ skips the token-generating probe on ALTERNATING
                # sweeps — half the optional probe load, but a broken
                # dataplane whose failures ARE the burn still produces
                # probe evidence (demotion + supervisor restart) within
                # two sweeps. A DEMOTED replica always probes —
                # re-admission still requires a clean pass through the
                # full dataplane. REMOTE replicas always probe too:
                # their probe is a cheap health GET, not a generation,
                # and it is the ONLY path that refreshes the cached
                # brownout/compliance advertisement — skipping it would
                # freeze a recovered pod at its last advertised level
                # forever.
                self._brownout_probe_skipped.add(id(replica))
                self._note_brownout_action(replica, "skip_probe")
                results[replica.name] = "skipped: brownout"
            else:
                results[replica.name] = self._probe_replica(replica)
            self._publish_state(replica)
        self.publish_pool_gauges()
        self._publish_tier_mode()
        return results

    def _probe_replica(self, replica: Replica) -> str:
        verdict, reason = replica.probe(self.probe_timeout_s)
        if verdict == "pass":
            if replica.probe_failed and self._logger is not None:
                self._logger.infof(
                    "probe: replica %s passed; re-admitted to routing",
                    replica.name,
                )
            replica.probe_failed = False
            replica.note_probe_success()
            return "pass"
        if verdict == "busy":
            # Overload is NOT failure: the replica is shedding/congested
            # under load, which demotion or a restart would only push
            # onto its siblings (restart cascade). Routing status stays
            # exactly as it was — a demoted replica still needs a clean
            # pass to come back.
            if self._logger is not None:
                self._logger.infof(
                    "probe: replica %s busy (%s); no action", replica.name,
                    reason,
                )
            return f"busy: {reason}"
        replica.probe_failed = True
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_probe_failures_total", "replica", replica.name
            )
        if self._logger is not None:
            self._logger.errorf(
                "probe: replica %s failed (%s); demoted from routing",
                replica.name, reason,
            )
        replica.notify_probe_failure(reason)
        return f"fail: {reason}"

    def start_prober(self) -> "ReplicaPool":
        if self.probe_interval_s <= 0:
            return self
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return self
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="tpu-replica-prober", daemon=True
        )
        self._probe_thread.start()
        return self

    def stop_prober(self) -> None:
        self._probe_stop.set()
        thread = self._probe_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
        self._probe_thread = None

    def _probe_loop(self) -> None:
        while True:
            # Jittered interval: a fleet of pools must not probe (or
            # restart) in lockstep.
            delay = self.probe_interval_s * (0.5 + self._rng.random())
            if self._probe_stop.wait(delay):
                return
            try:
                self.probe_once()
            except Exception as exc:  # noqa: BLE001 — the prober must survive
                if self._logger is not None:
                    self._logger.errorf("replica probe sweep failed: %s", exc)

    # -- health -----------------------------------------------------------

    def _publish_state(self, replica: Replica) -> None:
        if self._metrics is None:
            return
        value = (
            _STATE_ORDER["DOWN"] if replica.probe_failed
            else _STATE_ORDER.get(replica.state(), 3)
        )
        self._metrics.set_gauge(
            "app_tpu_replica_state", value, "replica", replica.name
        )

    @property
    def state(self) -> str:
        """Pool-level state machine: SERVING while ANY replica serves —
        single-replica loss is the pool's job to absorb."""
        states = [
            "DOWN" if r.probe_failed else r.state() for r in self._replicas
        ]
        if "SERVING" in states:
            return "SERVING"
        if "DEGRADED" in states or "RESTARTING" in states:
            return "DEGRADED"
        return "DOWN"

    def import_payload(self, payload: Any) -> str:
        """Wire-leg admission facade: a remote prefill pod POSTed KV
        blocks at this pod's ops-port import endpoint and
        ``container.tpu`` is a pool — land them on the in-proc replica
        the companion request will actually DECODE on. Decode-role
        replicas are tried first (on a pod that is itself tiered, the
        prefill replica's radix would be a paid-for warm nobody
        reads), and a replica that rejects the payload (unpaged
        engine, stale geometry) does not stop a paged sibling from
        importing it; each engine validates geometry + checksum
        exactly like an in-proc handoff, and a rejecting engine queues
        nothing, so offering the payload down the list is side-effect
        free. No importer anywhere → ``"rejected"`` (the exporter
        degrades to the next rung)."""
        best = "rejected"
        ranked = sorted(
            self._replicas, key=lambda r: 0 if r.role == "decode" else 1
        )
        for replica in ranked:
            if replica.draining:
                continue
            eng = getattr(replica, "engine", None)
            fn = getattr(eng, "import_payload", None)
            if not callable(fn):
                continue
            verdict = str(fn(payload))
            if verdict == "imported":
                return verdict
            if best == "rejected":
                best = verdict
        return best

    def flight_records(self) -> dict:
        """Aggregate ``/debug/flight`` view: each in-proc replica's
        flight recorder keyed by replica name, stamped with the
        replica's routing state and advertised adapter set (so an
        operator reading a failover record can see WHERE the adapter's
        weights lived at the time). Remote replicas contribute their
        descriptor (their own recorder lives on their ops port). A
        request that failed over appears ONCE — in its origin replica's
        recorder, with the failover annotation naming the adopter."""
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            fn = getattr(replica, "engine", None)
            records = getattr(fn, "flight_records", None)
            if callable(records):
                try:
                    entry = dict(records())
                except Exception as exc:  # noqa: BLE001 — debug surface
                    entry = {"error": str(exc)}
            else:
                entry = {"remote": True}
            entry["state"] = (
                "DOWN" if replica.probe_failed
                else ("DRAINING" if replica.draining else replica.state())
            )
            entry["adapters"] = sorted(replica.adapters())
            entry["role"] = replica.role
            # Pod shape (GSPMD-sharded serving): dp across replicas,
            # tp within each — None for unsharded replicas.
            entry["mesh"] = replica.mesh_topology()
            # Saturation headline (device_telemetry): flight readers
            # chasing tail latency see each replica's HBM pressure
            # next to its timelines — and whether its SLOs are burning.
            entry["hbm_headroom"] = replica.headroom()
            entry["slo_compliant"] = replica.slo_compliant()
            entry["brownout_level"] = replica.brownout_level()
            replicas[replica.name] = entry
        return {"replicas": replicas, "tier_mode": self.tier_mode}

    def capacity_report(self) -> dict:
        """Aggregate ``/debug/capacity`` view: each in-proc replica's
        device-resource report (HBM ledger, compile counts, paged-pool
        pressure) keyed by replica name, stamped with routing state and
        tier role. Remote replicas contribute their cached headroom —
        their full report lives on their own ops port."""
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            engine = getattr(replica, "engine", None)
            report_fn = getattr(engine, "capacity_report", None)
            if callable(report_fn):
                try:
                    entry = dict(report_fn())
                except Exception as exc:  # noqa: BLE001 — debug surface
                    entry = {"error": str(exc)}
            else:
                entry = {"remote": True}
            entry["state"] = (
                "DOWN" if replica.probe_failed
                else ("DRAINING" if replica.draining else replica.state())
            )
            entry["role"] = replica.role
            entry["hbm_headroom"] = replica.headroom()
            entry["slo_compliant"] = replica.slo_compliant()
            entry["brownout_level"] = replica.brownout_level()
            replicas[replica.name] = entry
        return {"replicas": replicas, "tier_mode": self.tier_mode}

    def _engine_reports(
        self,
        method: str,
        remote_entry: Callable[[Replica], "dict[str, Any]"],
        stamp_state: bool = True,
    ) -> dict:
        """The shared per-replica engine-report aggregation every
        ``*_report`` debug view uses: call ``method()`` on each in-proc
        replica's engine (errors become ``{"error": ...}`` — a debug
        surface must render a half-broken fleet, not 500), fall back to
        ``remote_entry(replica)`` for remotes (their full report lives
        on their own ops port), and optionally stamp routing state.
        One copy, so error handling and state stamping cannot drift
        between the five views."""
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            engine = getattr(replica, "engine", None)
            report_fn = getattr(engine, method, None)
            if callable(report_fn):
                try:
                    entry = dict(report_fn())
                except Exception as exc:  # noqa: BLE001 — debug surface
                    entry = {"error": str(exc)}
            else:
                entry = remote_entry(replica)
            if stamp_state:
                entry["state"] = (
                    "DOWN" if replica.probe_failed
                    else (
                        "DRAINING" if replica.draining
                        else replica.state()
                    )
                )
            replicas[replica.name] = entry
        return {"replicas": replicas}

    def tenant_report(self) -> dict:
        """Aggregate ``/debug/tenants`` view: each in-proc replica's
        tenant ledger keyed by replica name (remote replicas contribute
        their descriptor — their full table lives on their own ops
        port), so "which tenant holds the pool" has a fleet answer."""
        return self._engine_reports(
            "tenant_report", lambda replica: {"remote": True}
        )

    def slo_report(self) -> dict:
        """Aggregate ``/debug/slo`` view: each in-proc replica's
        burn-rate state keyed by replica name; remote replicas
        contribute their probe-cached compliance bit."""
        return self._engine_reports(
            "slo_report",
            lambda replica: {
                "remote": True,
                "compliant": replica.slo_compliant(),
            },
            stamp_state=False,
        )

    def brownout_report(self) -> dict:
        """Aggregate ``/debug/brownout`` view: each in-proc replica's
        ladder state keyed by replica name; remote replicas contribute
        their probe-cached level."""
        return self._engine_reports(
            "brownout_report",
            lambda replica: {
                "remote": True,
                "level": replica.brownout_level(),
            },
            stamp_state=False,
        )

    def loop_report(self) -> dict:
        """Aggregate ``/debug/loop`` view: each in-proc replica's
        scheduler-loop profiler state keyed by replica name (remote
        replicas contribute their descriptor — their profiler lives on
        their own ops port), so "which replica's loop is stalling" has
        a fleet answer."""
        return self._engine_reports(
            "loop_report", lambda replica: {"remote": True}
        )

    def health_check(self) -> dict:
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            detail = replica.describe()
            if isinstance(replica, EngineReplica):
                sup = getattr(replica.engine, "_supervisor", None)
                if sup is not None:
                    detail["supervisor"] = sup.describe()
            replicas[replica.name] = detail
            self._publish_state(replica)
        self.publish_pool_gauges()
        pool_state = self.state
        serving = sum(
            1 for r in self._replicas
            if not r.probe_failed and r.state() == "SERVING"
        )
        return {
            "status": "UP" if pool_state == "SERVING" else "DOWN",
            "state": pool_state,
            "details": {
                "model": self.model_name,
                "family": self.family,
                "replicas": replicas,
                "serving": serving,
                "total": len(self._replicas),
                "hedge_budget": round(self.hedge_budget.available(), 3),
                "tier_mode": self.tier_mode,
                "tier_sources": [r.name for r in self.tier_sources()],
            },
        }
