"""Replica-tier failover: health-aware routing around DOWN engines.

PR 3 made a SINGLE engine self-healing — but a replica that exhausts
``TPU_RESTART_MAX`` still lands DOWN and takes its traffic with it. This
module is the layer above: a :class:`ReplicaPool` fronts N inference
backends (in-process :class:`~gofr_tpu.serving.engine.InferenceEngine`
replicas and/or remote ``HTTPService`` endpoints) and makes the POOL the
availability boundary, the way vLLM/Pathways-style deployments treat
the router rather than the engine as the unit that must never die.

What the pool owns:

* **Health-aware routing** — every submit picks the least-loaded
  replica among SERVING ones (round-robin tie-break so equal-load
  replicas share traffic), spills to DEGRADED when nothing is SERVING,
  and never routes to RESTARTING/DOWN or probe-demoted replicas. With
  no routable replica at all, submits fail fast with
  :class:`~gofr_tpu.errors.ErrorNoHealthyReplica` (502 — the routing
  tier found no upstream) instead of queueing into a dead engine.
* **Mid-stream failover** — each in-proc replica gets a *handoff*: when
  an engine's supervisor gives up (crash loop → DOWN) or a scheduler
  dies unsupervised, still-retryable requests are offered to the pool,
  which requeues the SAME request object on a sibling replica via
  ``engine.requeue_replay``. The client's stream queue and future carry
  over; admission re-prefills prompt + already-delivered tokens and the
  sampling-counter offset restores the seeded sample path, so the SSE
  stream continues byte-identically — no 5xx, no duplicate tokens.
* **Hedged unary retries** — :meth:`ReplicaPool.generate_sync` (and the
  async ``generate``) races a second replica when the primary is slow
  (jittered ``TPU_HEDGE_DELAY_S``) or retries when it fails fast; both
  spend from a token-bucket :class:`~gofr_tpu.serving.lifecycle.
  HedgeBudget` (``TPU_HEDGE_BUDGET``) so hedging can never double load
  on an already-slow tier, and are deadline-aware. Per-replica circuit
  breakers stay where they are — an open breaker's fast-fail is simply
  one more signal the router reroutes on, not a second breaker.
* **Active probing** — a jittered-interval prober issues one cheap
  synthetic generation per replica (``engine.synthetic_probe``: one
  greedy token through the full dataplane). A failed probe demotes the
  replica (routed around even if it still CLAIMS SERVING) and asks its
  supervisor to restart — recovery on evidence, not just on crash. A
  DOWN replica is revived and **re-admitted only after a passing
  probe**; a passing probe also resets the supervisor's crash-loop
  counter and half-opens a stuck circuit breaker.

Observability: ``app_tpu_replica_state`` (0=SERVING 1=DEGRADED
2=RESTARTING 3=DOWN per replica), ``app_tpu_failovers_total``,
``app_tpu_probe_failures_total``, ``app_tpu_hedged_requests_total``.

Determinism contract (the chaos suite, ``tests/test_replica_pool.py``):
clock/rng are injectable, the prober thread is optional (tests call
``probe_once()``), and nothing here sleeps on the request path.

Cross-replica replay only produces *byte-identical* continuations when
sibling replicas share params and the engine seed (the same
``TPU_SEED``); with distinct seeds the continuation is still a valid
sample path, just a different one.
"""

from __future__ import annotations

import concurrent.futures as cf
import random
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence

from gofr_tpu.errors import (
    ErrorDeadlineExceeded,
    ErrorNoHealthyReplica,
    ErrorTooManyRequests,
)
from gofr_tpu.serving.lifecycle import Deadline, HedgeBudget

#: Gauge encoding shared with app_tpu_engine_state.
_STATE_ORDER = {"SERVING": 0, "DEGRADED": 1, "RESTARTING": 2, "DOWN": 3}

#: Statuses a sibling replica may retry/hedge: per-replica overload or
#: failure. 4xx validation errors and 504 (the CALLER's deadline) are
#: the same on every replica and never rerouted.
_REROUTE_STATUSES = frozenset((429, 500, 502, 503))


def _is_reroutable(exc: BaseException) -> bool:
    return int(getattr(exc, "status_code", 500)) in _REROUTE_STATUSES


class Replica:
    """One pool member. Subclasses bind a concrete backend."""

    #: Streaming + request adoption need an in-process engine.
    supports_stream = False

    def __init__(self, name: str) -> None:
        self.name = name
        # Latched by a failed synthetic probe; cleared ONLY by a passing
        # one. While set, the router treats the replica as DOWN no
        # matter what its own state machine claims.
        self.probe_failed = False

    # -- routing surface ------------------------------------------------

    def state(self) -> str:
        raise NotImplementedError

    def load(self) -> int:
        """Outstanding work (queue + live); the least-loaded heuristic."""
        raise NotImplementedError

    def throughput(self) -> float:
        """Measured tokens/sec (sliding window), 0.0 when unknown — the
        weighted router divides outstanding work by this to estimate
        completion time. Replicas without a signal (cold engines, unary
        HTTP backends) share a common floor, which degrades weighted
        routing to the plain least-loaded pick."""
        return 0.0

    def submit(self, prompt: Any, **kw: Any) -> Any:
        """Submit a generation; returns a ``_GenRequest``-shaped handle
        (``.future``, ``.stream``, ``.cancel_request()``)."""
        raise NotImplementedError

    def adopt(self, req: Any) -> bool:
        """Continue a salvaged request from a dying sibling (stream and
        future intact). False when this backend cannot."""
        return False

    # -- probe surface ----------------------------------------------------

    def probe(self, timeout_s: float) -> tuple[str, str]:
        """One synthetic end-to-end check → ``(verdict, reason)`` with
        verdict ``"pass"`` (healthy), ``"busy"`` (overloaded — shedding
        or congested, which is a HEALTHY engine doing its job, never
        grounds for demotion or a restart), or ``"fail"`` (broken)."""
        raise NotImplementedError

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        """Attempt to bring a DOWN backend back for probation."""
        return False

    def note_probe_success(self) -> None:
        """Propagate a passing probe (supervisor counter reset, breaker
        half-open, ...)."""

    def notify_probe_failure(self, reason: str) -> None:
        """Propagate a failing probe (supervisor restart request)."""

    def describe(self) -> dict:
        return {
            "state": self.state(),
            "probe_failed": self.probe_failed,
            "load": self.load(),
            "supports_stream": self.supports_stream,
        }

    def close(self) -> None:
        pass


class EngineReplica(Replica):
    """An in-process :class:`InferenceEngine` (plus its supervisor)."""

    supports_stream = True

    def __init__(self, name: str, engine: Any) -> None:
        super().__init__(name)
        self.engine = engine

    def state(self) -> str:
        return str(self.engine.state)

    def load(self) -> int:
        eng = self.engine
        if getattr(eng, "family", "llm") != "llm":
            return 0
        # Lock-free host reads — a one-iteration-stale count is fine for
        # a routing heuristic.
        queued = eng._pending.qsize() + len(eng._wait_kv)
        live = sum(1 for s in eng._slots if s is not None)
        return queued + live + len(eng._prefilling)

    def throughput(self) -> float:
        # The engine's sliding-window AGGREGATE tokens/sec — the same
        # lifecycle.AggregateThroughput estimate its own projected-wait
        # shedder divides by. 0.0 while cold (no emissions in window).
        tput = getattr(self.engine, "_tput", None)
        if tput is None:
            return 0.0
        try:
            return float(tput.rate())
        except Exception:  # noqa: BLE001 — heuristic only, never break routing
            return 0.0

    def submit(self, prompt: Any, **kw: Any) -> Any:
        return self.engine.submit_generate(prompt, **kw)

    def adopt(self, req: Any) -> bool:
        return bool(self.engine.requeue_replay(req))

    def probe(self, timeout_s: float) -> tuple[str, str]:
        from gofr_tpu.errors import (
            ErrorDeadlineExceeded,
            ErrorTooManyRequests,
        )

        try:
            self.engine.synthetic_probe(timeout_s=timeout_s)
            return "pass", ""
        except (ErrorTooManyRequests, ErrorDeadlineExceeded) as exc:
            # Admission SHED the probe: overload, not breakage — a
            # replica answering 429s is exactly what load shedding is
            # for, and demoting/restarting it would cascade the load
            # onto its siblings until the whole pool restarts.
            return "busy", f"{type(exc).__name__}: {exc}"
        except cf.TimeoutError as exc:
            if self.load() > 1:
                # The probe queued behind real work: congested, not
                # dead. A wedged scheduler is the watchdog's job.
                return "busy", f"probe timed out behind {self.load()} waiting"
            return "fail", f"probe timed out on an idle engine: {exc}"
        except Exception as exc:  # noqa: BLE001 — ANY other failure demotes the replica
            return "fail", f"{type(exc).__name__}: {exc}"

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            return bool(sup.revive())
        try:
            self.engine.restart_sync()
            return True
        except Exception:  # noqa: BLE001 — a failed revive keeps the replica DOWN
            return False

    def note_probe_success(self) -> None:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            sup.note_probe_success()

    def notify_probe_failure(self, reason: str) -> None:
        sup = getattr(self.engine, "_supervisor", None)
        if sup is not None:
            sup.notify_probe_failure(reason)

    def close(self) -> None:
        self.engine.set_replica_handoff(None)
        self.engine.close()


class HTTPReplica(Replica):
    """A remote replica behind the service tier: unary generations via
    its OpenAI-compatible endpoint, liveness via ``/.well-known/health``.

    Compose the service with :class:`CircuitBreakerConfig`/auth options
    at construction — the pool does not duplicate the breaker, it
    reroutes on its fast-fails and half-opens it on passing probes.
    Streams and request adoption stay on in-proc replicas: a remote
    engine's stream cannot adopt another replica's live queue handle.
    """

    supports_stream = False

    def __init__(
        self,
        name: str,
        service: Any,
        *,
        generate_path: str = "v1/completions",
    ) -> None:
        super().__init__(name)
        self.service = service
        self.generate_path = generate_path
        self._lock = threading.Lock()
        self._inflight = 0
        self._state = "SERVING"

    def state(self) -> str:
        return self._state

    def load(self) -> int:
        with self._lock:
            return self._inflight

    def submit(self, prompt: Any, **kw: Any) -> Any:
        from gofr_tpu.serving.types import _GenRequest

        req = _GenRequest(
            prompt_ids=list(prompt) if not isinstance(prompt, str) else [],
            max_new_tokens=int(kw.get("max_new_tokens", 128)),
            temperature=float(kw.get("temperature", 0.0)),
            stop_on_eos=bool(kw.get("stop_on_eos", True)),
        )
        deadline = kw.get("deadline")
        with self._lock:
            self._inflight += 1
        worker = threading.Thread(
            target=self._run_unary,
            args=(req, prompt, kw, deadline),
            name=f"http-replica-{self.name}",
            daemon=True,
        )
        worker.start()
        return req

    def _run_unary(
        self, req: Any, prompt: Any, kw: dict, deadline: Optional[Deadline]
    ) -> None:
        from gofr_tpu.errors import ErrorServiceUnavailable
        from gofr_tpu.serving.types import GenerationResult

        start = time.monotonic()
        try:
            body: dict[str, Any] = {
                "prompt": prompt,
                "max_tokens": int(kw.get("max_new_tokens", 128)),
                "temperature": float(kw.get("temperature", 0.0)),
                "stream": False,
            }
            # Forward the FULL sampling contract: a remote replica that
            # silently dropped logit_bias/penalties/adapter would serve
            # differently-sampled (or base-model) output with a 200.
            for src, dst in (
                ("top_p", "top_p"), ("stop", "stop"), ("seed", "seed"),
                ("logit_bias", "logit_bias"),
                ("frequency_penalty", "frequency_penalty"),
                ("presence_penalty", "presence_penalty"),
                ("top_logprobs", "top_logprobs"),
                # A loaded LoRA adapter's name IS a model on the OpenAI
                # surface (this repo's own openai_compat convention).
                ("adapter", "model"),
            ):
                if kw.get(src):
                    body[dst] = kw[src]
            headers: dict[str, str] = {}
            if deadline is not None:
                headers["X-Request-Timeout"] = str(
                    max(deadline.remaining(), 0.001)
                )
            if kw.get("tenant"):
                headers["X-Tenant-Id"] = str(kw["tenant"])
            if kw.get("traceparent"):
                # Cross-replica trace stitching: the remote replica's
                # server middleware adopts this trace id, so its spans
                # land in the SAME trace as the routing tier's.
                headers["traceparent"] = str(kw["traceparent"])
            resp = self.service.post(
                self.generate_path, json=body, headers=headers
            )
            if resp.status_code >= 400:
                if resp.status_code == 429:
                    raise ErrorTooManyRequests(
                        f"replica {self.name} shed the request",
                        retry_after_s=float(
                            resp.get_header("Retry-After") or 1.0
                        ),
                    )
                if resp.status_code >= 500:
                    raise ErrorServiceUnavailable(
                        f"replica {self.name} answered {resp.status_code}"
                    )
                # Request-shaped 4xx (400/404/413/...): surface the
                # UPSTREAM's status untouched — the request would fail
                # identically on every replica, so it must not become a
                # reroutable 503 and bounce around the pool.
                from gofr_tpu.errors import GofrError

                exc = GofrError(
                    f"replica {self.name} answered {resp.status_code}: "
                    f"{resp.body[:200].decode(errors='replace')}"
                )
                exc.status_code = resp.status_code
                raise exc
            data = resp.json()
            if isinstance(data, dict) and "choices" not in data:
                data = data.get("data", data)  # unwrap gofr envelopes
            choice = (data.get("choices") or [{}])[0]
            usage = data.get("usage") or {}
            result = GenerationResult(
                text=str(choice.get("text", "")),
                token_ids=[],
                prompt_tokens=int(usage.get("prompt_tokens", 0)),
                ttft_s=0.0,
                duration_s=time.monotonic() - start,
                finish_reason=str(choice.get("finish_reason", "stop")),
            )
            if not req.future.done():
                req.future.set_result(result)
        except Exception as exc:  # noqa: BLE001 — every failure must reach the caller
            try:
                if not req.future.done():
                    req.future.set_exception(exc)
            except Exception:  # noqa: BLE001 — future cancelled concurrently
                pass
        finally:
            with self._lock:
                self._inflight -= 1
            req.stream.put(None)

    def probe(self, timeout_s: float) -> tuple[str, str]:
        try:
            health = self.service.health_check()
        except Exception as exc:  # noqa: BLE001 — unreachable == failed probe
            health = {"status": "DOWN", "details": {"error": str(exc)}}
        if health.get("status") == "UP":
            self._state = "SERVING"
            return "pass", ""
        self._state = "DOWN"
        return "fail", str(health.get("details", {}).get("error", "DOWN"))

    def revive(self, probe_timeout_s: float = 5.0) -> bool:
        verdict, _ = self.probe(timeout_s=probe_timeout_s)
        return verdict == "pass"

    def note_probe_success(self) -> None:
        # Half-open a stuck breaker anywhere in the option chain: the
        # probe proved the address serves again (circuit_breaker.py).
        svc = self.service
        while svc is not None:
            hook = getattr(svc, "note_probe_success", None)
            if callable(hook):
                hook()
            svc = getattr(svc, "_inner", None)

    def close(self) -> None:
        close = getattr(self.service, "close", None)
        if callable(close):
            close()


class ReplicaPool:
    """Engine-shaped facade over N replicas (drop-in for
    ``container.tpu``: the OpenAI routes and both gRPC servicers serve
    through it unchanged)."""

    def __init__(
        self,
        replicas: Sequence[Replica],
        *,
        # Hedge only requests slower than a typical healthy completion:
        # multi-token generations run seconds, and a sub-second default
        # would hedge nearly EVERY request on a healthy pool.
        hedge_delay_s: float = 2.0,
        hedge_budget: Optional[HedgeBudget] = None,
        probe_interval_s: float = 30.0,
        probe_timeout_s: float = 30.0,
        weighted: bool = True,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        if not replicas:
            raise ValueError("a replica pool needs at least one replica")
        self._replicas = list(replicas)
        # Weighted routing (TPU_ROUTE_WEIGHTED, default on): pick by
        # least ESTIMATED COMPLETION TIME — outstanding work over the
        # replica's measured tokens/sec — instead of raw queue length,
        # so a replica decoding 2× faster absorbs ~2× the traffic.
        # Replicas with no throughput signal share a common default, in
        # which case the pick degrades to exactly the least-loaded one.
        self.weighted = bool(weighted)
        self.hedge_delay_s = max(0.0, float(hedge_delay_s))
        self.hedge_budget = (
            hedge_budget if hedge_budget is not None
            else HedgeBudget(clock=clock)
        )
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._metrics = metrics
        self._logger = logger
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._primary_engine = next(
            (r.engine for r in self._replicas
             if isinstance(r, EngineReplica)),
            None,
        )
        # Mid-stream failover: each in-proc engine offers the pool its
        # otherwise-terminal retryable requests (engine.try_handoff →
        # here → sibling.adopt == requeue_replay).
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                replica.engine.set_replica_handoff(
                    self._make_handoff(replica)
                )

    # -- engine facade ----------------------------------------------------

    @property
    def family(self) -> str:
        eng = self._primary_engine
        return str(eng.family) if eng is not None else "llm"

    @property
    def model_name(self) -> str:
        eng = self._primary_engine
        if eng is not None:
            return str(eng.model_name)
        return self._replicas[0].name

    @property
    def tokenizer(self) -> Any:
        eng = self._primary_engine
        return eng.tokenizer if eng is not None else None

    @property
    def replicas(self) -> list[Replica]:
        return list(self._replicas)

    def __getattr__(self, name: str) -> Any:
        # Everything the pool does not reinterpret (lora_names,
        # max_prompt_tokens, embed, register_prefix, ...) delegates to
        # the primary in-proc engine — the pool is an ENGINE-shaped
        # object to its callers. (Only reached for attributes not
        # defined on the pool itself.)
        eng = self.__dict__.get("_primary_engine")
        if eng is not None and not name.startswith("__"):
            return getattr(eng, name)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self.start_sync()

    def start_sync(self) -> None:
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                replica.engine.start_sync()
        self.start_prober()

    async def stop(self, drain_s: float = 0.0) -> None:
        self.stop_prober()
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                # Detach the handoff FIRST: a pool-wide shutdown must
                # terminate in-flight work, not migrate it replica to
                # replica (re-decoding delivered prefixes and emitting
                # phantom failover metrics during a routine deploy).
                replica.engine.set_replica_handoff(None)
        for replica in self._replicas:
            if isinstance(replica, EngineReplica):
                replica.engine.stop_sync(drain_s)

    def close(self) -> None:
        self.stop_prober()
        for replica in self._replicas:
            try:
                replica.close()
            except Exception as exc:  # noqa: BLE001 — close every replica regardless
                if self._logger is not None:
                    self._logger.errorf(
                        "replica %s close failed: %s", replica.name, exc
                    )

    # -- routing ----------------------------------------------------------

    def pick(
        self,
        exclude: Iterable[Replica] = (),
        *,
        require_stream: bool = False,
    ) -> Replica:
        """Least-loaded routable replica: SERVING first, spill to
        DEGRADED, never RESTARTING/DOWN or probe-demoted. Round-robin
        rotation breaks load ties so equal replicas share traffic.
        ``require_stream`` restricts to stream-capable (in-proc)
        backends — a unary-only HTTPReplica handed a streaming request
        would answer a 200 SSE with zero tokens, which is worse than an
        honest 502.

        Weighted mode ranks by estimated completion time instead:
        ``(load + 1) / measured tokens/sec`` — the ROADMAP follow-up to
        queue-length routing; with no throughput signal anywhere it
        collapses to the same least-loaded pick."""
        excluded = {id(r) for r in exclude}

        def routable(states: tuple[str, ...]) -> list[Replica]:
            return [
                r for r in self._replicas
                if id(r) not in excluded
                and not r.probe_failed
                and (r.supports_stream or not require_stream)
                and r.state() in states
            ]

        candidates = routable(("SERVING",)) or routable(("DEGRADED",))
        if candidates:
            with self._rr_lock:
                start = self._rr % len(candidates)
                self._rr += 1
            rotated = candidates[start:] + candidates[:start]
            if not self.weighted:
                return min(rotated, key=lambda r: r.load())
            return min(rotated, key=self._completion_score(rotated))
        raise ErrorNoHealthyReplica(
            f"{len(self._replicas)} replica(s), none "
            + ("stream-capable and " if require_stream else "")
            + "SERVING or DEGRADED"
        )

    @staticmethod
    def _completion_score(
        candidates: Sequence[Replica],
    ) -> Callable[[Replica], float]:
        """Least-estimated-completion-time key: outstanding work (+1
        for the request being placed) over measured tokens/sec.
        Replicas without a signal (cold, unary HTTP) are assumed as
        fast as the FASTEST measured sibling — a cold replica is
        usually an idle one, and penalizing it would starve it of the
        traffic that would produce its first measurement. All-unknown
        → every rate equal → ordering identical to least-loaded."""
        rates = {id(r): max(0.0, r.throughput()) for r in candidates}
        known = [v for v in rates.values() if v > 0.0]
        default = max(known) if known else 1.0

        def score(r: Replica) -> float:
            rate = rates.get(id(r), 0.0) or default
            return (r.load() + 1) / rate

        return score

    def _submit_routed(
        self,
        prompt: Any,
        kw: dict,
        tried: list[Replica],
        *,
        require_stream: bool,
    ) -> tuple[Replica, Any]:
        """Submit with failover across replicas: per-replica overload or
        failure (429/5xx, open breaker) reroutes to the next candidate;
        request-shaped errors (400/413/...) raise immediately — they
        would fail identically everywhere."""
        last: Optional[BaseException] = None
        while True:
            try:
                replica = self.pick(
                    exclude=tried, require_stream=require_stream
                )
            except ErrorNoHealthyReplica:
                if isinstance(last, ErrorTooManyRequests):
                    raise last from None  # keep the 429 + Retry-After
                if last is not None:
                    raise ErrorNoHealthyReplica(str(last)) from last
                raise
            tried.append(replica)
            try:
                return replica, replica.submit(prompt, **kw)
            except Exception as exc:
                if not _is_reroutable(exc):
                    raise
                last = exc
                if self._logger is not None:
                    self._logger.warnf(
                        "replica %s rejected a submit (%s); rerouting",
                        replica.name, exc,
                    )

    def submit_generate(self, prompt: Any, **kw: Any) -> Any:
        """Route one generation. The returned handle's STREAM must work
        (callers can't say whether they will iterate it), so only
        stream-capable in-proc replicas qualify; unary-only HTTPReplicas
        serve through :meth:`generate_sync`/:meth:`generate` instead.
        Mid-stream replica loss is handled by the handoff path, not
        here."""
        _, req = self._submit_routed(prompt, kw, [], require_stream=True)
        return req

    # -- unary with hedged retries ---------------------------------------

    def _hedge_delay(self, deadline: Optional[Deadline]) -> float:
        """Jittered hedge trigger, clamped under the caller's deadline."""
        delay = self.hedge_delay_s * (0.75 + 0.5 * self._rng.random())
        if deadline is not None:
            delay = min(delay, max(deadline.remaining(), 0.0))
        return delay

    def should_hedge(self, deadline: Optional[Deadline]) -> bool:
        """Deadline-aware, budgeted second-attempt decision (latency
        hedges AND fast-fail retries): never hedge work whose deadline
        already passed, and never without budget — an exhausted bucket
        means the tier is slow EVERYWHERE and doubling load would dig
        the hole deeper."""
        if deadline is not None and deadline.remaining() <= 0:
            return False
        return self.hedge_budget.try_acquire()

    def _count_hedge(self, kind: str, kw: Optional[dict] = None) -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_hedged_requests_total", "kind", kind
            )
        # Trace annotation: the hedge/retry hop lands in the request's
        # trace (instant span under the caller's traceparent). No-op
        # without an active exporter.
        from gofr_tpu.serving.observability import emit_instant_span

        emit_instant_span(
            "tpu.hedge",
            (kw or {}).get("traceparent"),
            {"kind": kind},
        )

    def generate_sync(
        self, prompt: Any, timeout: float = 300.0, **kw: Any
    ) -> Any:
        """Unary generation with bounded hedged retries: a slow primary
        is raced by one budgeted hedge on a different replica (first
        success wins, the loser is cancelled); a fast-failing primary is
        retried once on a sibling. Composes with per-replica circuit
        breakers — their fast-fails are reroute signals here."""
        deadline = kw.get("deadline")
        tried: list[Replica] = []
        _, req = self._submit_routed(prompt, kw, tried, require_stream=False)
        live = [req]
        primary_exc: Optional[BaseException] = None
        try:
            return req.future.result(timeout=self._hedge_delay(deadline))
        except cf.TimeoutError:
            pass  # primary slow → consider a latency hedge below
        except cf.CancelledError:
            live, primary_exc = [], ErrorNoHealthyReplica("request cancelled")
        except Exception as exc:
            if not _is_reroutable(exc):
                raise
            live, primary_exc = [], exc  # primary failed fast → retry
        # Hedges AND fast-fail retries both spend from the SAME bucket:
        # under tier-wide overload, unbudgeted retries would double load
        # exactly when every replica is already failing. The sibling
        # check comes FIRST (short-circuit) so a pool with no routable
        # second replica never burns tokens it cannot use — draining the
        # bucket on impossible hedges would starve real ones the moment
        # a sibling recovers.
        if self._routable_sibling_exists(tried) and self.should_hedge(
            deadline
        ):
            try:
                _, second = self._submit_routed(
                    prompt, kw, tried, require_stream=False
                )
            except Exception as exc:  # noqa: BLE001 — ride the primary if no sibling
                if not live:
                    raise (primary_exc or exc)
            else:
                live.append(second)
                self._count_hedge(
                    "retry" if primary_exc is not None else "hedge", kw
                )
        elif not live:
            # Primary failed with no budgeted/routable second attempt:
            # fail honestly rather than amplify the overload.
            assert primary_exc is not None
            raise primary_exc
        return self._first_result(live, timeout, primary_exc)

    def _routable_sibling_exists(self, tried: list[Replica]) -> bool:
        excluded = {id(r) for r in tried}
        return any(
            id(r) not in excluded
            and not r.probe_failed
            and r.state() in ("SERVING", "DEGRADED")
            for r in self._replicas
        )

    def _first_result(
        self,
        reqs: list[Any],
        timeout: float,
        last_exc: Optional[BaseException],
    ) -> Any:
        """First successful attempt wins; losers are cancelled so no
        replica decodes for a caller that already has its answer."""
        end = time.monotonic() + timeout
        pending = list(reqs)
        while pending:
            by_future = {r.future: r for r in pending}
            done, _ = cf.wait(
                list(by_future),
                timeout=max(0.0, end - time.monotonic()),
                return_when=cf.FIRST_COMPLETED,
            )
            if not done:
                for r in pending:
                    r.cancel_request()
                raise ErrorDeadlineExceeded(
                    f"no replica answered within {timeout:.1f}s"
                )
            for future in done:
                pending.remove(by_future[future])
                try:
                    result = future.result()
                except Exception as exc:  # noqa: BLE001 — keep racing the others
                    last_exc = exc
                    continue
                for loser in pending:
                    loser.cancel_request()
                return result
        raise last_exc if last_exc is not None else ErrorNoHealthyReplica()

    async def generate(self, prompt: Any, **kw: Any) -> Any:
        import asyncio
        from functools import partial

        return await asyncio.get_running_loop().run_in_executor(
            None, partial(self.generate_sync, prompt, **kw)
        )

    async def generate_stream(
        self, prompt: Any, **kw: Any
    ) -> Any:
        """Async iterator over token ids (engine-API parity); replica
        loss mid-stream is healed by the handoff path underneath."""
        import asyncio

        req = self.submit_generate(prompt, **kw)
        loop = asyncio.get_running_loop()
        while True:
            tok = await loop.run_in_executor(None, req.stream.get)
            if tok is None:
                return
            yield tok

    # -- mid-stream failover (engine handoff target) ----------------------

    def _make_handoff(self, source: EngineReplica) -> Callable[[Any], bool]:
        def handoff(req: Any) -> bool:
            return self._failover(req, source)

        return handoff

    def _failover(self, req: Any, source: Replica) -> bool:
        """Adopt a salvaged request from a dying replica onto a healthy
        sibling. True = requeued (stream/future intact); False = the
        caller fails it through its terminal path."""
        tried: list[Replica] = [source]
        for _ in range(len(self._replicas)):
            try:
                # Adoption continues a live STREAM handle: in-proc only.
                replica = self.pick(exclude=tried, require_stream=True)
            except ErrorNoHealthyReplica:
                return False
            tried.append(replica)
            if not replica.adopt(req):
                continue
            timeline = getattr(req, "timeline", None)
            if timeline is not None:
                # Rides the request's lifecycle timeline: the failover
                # hop shows up in /debug/flight and as a span in the
                # request's ONE trace (emitted at retirement on the
                # adopting replica).
                timeline.note_failover(
                    source.name, replica.name, timeline.hub.now()
                )
            if self._metrics is not None:
                self._metrics.increment_counter(
                    "app_tpu_failovers_total",
                    "from", source.name, "to", replica.name,
                )
            if self._logger is not None:
                self._logger.infof(
                    "failover: request moved %s → %s (%d token(s) already "
                    "delivered)",
                    source.name, replica.name, len(req.token_ids),
                )
            return True
        return False

    # -- active probing ---------------------------------------------------

    def probe_once(self) -> dict[str, str]:
        """One synthetic-probe sweep (the prober thread's body; tests
        call it directly — no thread, no sleeps). Per replica:

        * RESTARTING — its supervisor is mid-recovery; leave it alone.
        * DOWN — demote, attempt a revive, then probe; only a PASSING
          probe re-admits it.
        * SERVING/DEGRADED — probe; a failure demotes it and requests a
          supervisor restart (restart on evidence, not just on crash).
        """
        results: dict[str, str] = {}
        for replica in self._replicas:
            state = replica.state()
            if state == "RESTARTING":
                results[replica.name] = "restarting"
            elif state == "DOWN":
                # Probation: only a PASSING probe re-admits a revived
                # replica (a merely-busy one stays out until it proves
                # the dataplane end to end).
                replica.probe_failed = True
                results[replica.name] = (
                    self._probe_replica(replica)
                    if replica.revive(self.probe_timeout_s) else "down"
                )
            else:
                results[replica.name] = self._probe_replica(replica)
            self._publish_state(replica)
        return results

    def _probe_replica(self, replica: Replica) -> str:
        verdict, reason = replica.probe(self.probe_timeout_s)
        if verdict == "pass":
            if replica.probe_failed and self._logger is not None:
                self._logger.infof(
                    "probe: replica %s passed; re-admitted to routing",
                    replica.name,
                )
            replica.probe_failed = False
            replica.note_probe_success()
            return "pass"
        if verdict == "busy":
            # Overload is NOT failure: the replica is shedding/congested
            # under load, which demotion or a restart would only push
            # onto its siblings (restart cascade). Routing status stays
            # exactly as it was — a demoted replica still needs a clean
            # pass to come back.
            if self._logger is not None:
                self._logger.infof(
                    "probe: replica %s busy (%s); no action", replica.name,
                    reason,
                )
            return f"busy: {reason}"
        replica.probe_failed = True
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_probe_failures_total", "replica", replica.name
            )
        if self._logger is not None:
            self._logger.errorf(
                "probe: replica %s failed (%s); demoted from routing",
                replica.name, reason,
            )
        replica.notify_probe_failure(reason)
        return f"fail: {reason}"

    def start_prober(self) -> "ReplicaPool":
        if self.probe_interval_s <= 0:
            return self
        if self._probe_thread is not None and self._probe_thread.is_alive():
            return self
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="tpu-replica-prober", daemon=True
        )
        self._probe_thread.start()
        return self

    def stop_prober(self) -> None:
        self._probe_stop.set()
        thread = self._probe_thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
        self._probe_thread = None

    def _probe_loop(self) -> None:
        while True:
            # Jittered interval: a fleet of pools must not probe (or
            # restart) in lockstep.
            delay = self.probe_interval_s * (0.5 + self._rng.random())
            if self._probe_stop.wait(delay):
                return
            try:
                self.probe_once()
            except Exception as exc:  # noqa: BLE001 — the prober must survive
                if self._logger is not None:
                    self._logger.errorf("replica probe sweep failed: %s", exc)

    # -- health -----------------------------------------------------------

    def _publish_state(self, replica: Replica) -> None:
        if self._metrics is None:
            return
        value = (
            _STATE_ORDER["DOWN"] if replica.probe_failed
            else _STATE_ORDER.get(replica.state(), 3)
        )
        self._metrics.set_gauge(
            "app_tpu_replica_state", value, "replica", replica.name
        )

    @property
    def state(self) -> str:
        """Pool-level state machine: SERVING while ANY replica serves —
        single-replica loss is the pool's job to absorb."""
        states = [
            "DOWN" if r.probe_failed else r.state() for r in self._replicas
        ]
        if "SERVING" in states:
            return "SERVING"
        if "DEGRADED" in states or "RESTARTING" in states:
            return "DEGRADED"
        return "DOWN"

    def flight_records(self) -> dict:
        """Aggregate ``/debug/flight`` view: each in-proc replica's
        flight recorder keyed by replica name. A request that failed
        over appears ONCE — in its origin replica's recorder, with the
        failover annotation naming the adopting replica."""
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            fn = getattr(replica, "engine", None)
            records = getattr(fn, "flight_records", None)
            if callable(records):
                try:
                    replicas[replica.name] = records()
                except Exception as exc:  # noqa: BLE001 — debug surface
                    replicas[replica.name] = {"error": str(exc)}
        return {"replicas": replicas}

    def health_check(self) -> dict:
        replicas: dict[str, Any] = {}
        for replica in self._replicas:
            detail = replica.describe()
            if isinstance(replica, EngineReplica):
                sup = getattr(replica.engine, "_supervisor", None)
                if sup is not None:
                    detail["supervisor"] = sup.describe()
            replicas[replica.name] = detail
            self._publish_state(replica)
        pool_state = self.state
        serving = sum(
            1 for r in self._replicas
            if not r.probe_failed and r.state() == "SERVING"
        )
        return {
            "status": "UP" if pool_state == "SERVING" else "DOWN",
            "state": pool_state,
            "details": {
                "model": self.model_name,
                "family": self.family,
                "replicas": replicas,
                "serving": serving,
                "total": len(self._replicas),
                "hedge_budget": round(self.hedge_budget.available(), 3),
            },
        }
