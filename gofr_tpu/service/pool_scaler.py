"""Load-adaptive replica-pool scaling (``PoolScaler``).

The :class:`~gofr_tpu.service.replica_pool.ReplicaPool` made the pool —
not the engine — the availability boundary; this module makes it the
CAPACITY boundary too. A :class:`PoolScaler` watches the load signals
the pool already exposes (aggregate outstanding work per serving
replica, measured throughput) and resizes the pool through two
injectable callbacks:

* ``spawn() -> Replica`` — build one new replica. Tests and
  single-host deployments pass an in-proc engine factory
  (``serving/backend.py`` wires exactly that from config); real
  multi-host deployments pass an operator hook that provisions a pod
  and returns an ``HTTPReplica`` pointing at it.
* drain — not a callback but a protocol: scale-down picks the idlest
  eligible replica and runs the pool's ``drain_replica`` (stop routing
  → bounded in-flight completion → retire). A drain that cannot reach
  zero load inside its budget ABORTS and re-admits the replica, so
  scaling down never drops an in-flight request.

Decision rule (deliberately boring — autoscalers earn trust by being
predictable):

* **Scale up** when outstanding work per serving replica stays above
  ``up_load_per_replica`` for ``scale_up_wait_s`` continuously
  (``TPU_SCALE_UP_WAIT_S``) and the pool is below
  ``max_replicas`` (``TPU_POOL_MAX_REPLICAS``).
* **Scale down** when it stays below ``down_load_per_replica`` for
  ``scale_down_wait_s`` continuously (``TPU_SCALE_DOWN_WAIT_S``) and
  the pool is above ``min_replicas`` (``TPU_POOL_MIN_REPLICAS``).
* Replicas that are draining, probe-demoted, or DOWN don't count as
  capacity — a pool of three replicas with two DOWN is a one-replica
  pool under this rule, which is exactly when you want the spawn.

The sustain windows are the flap guard: a single bursty sweep neither
spawns (cold engines take seconds to compile) nor drains (the burst's
tail would land on fewer replicas). Hysteresis comes from the gap
between the two thresholds.

Determinism contract (``tests/test_remote_failover.py``): the clock is
injectable, ``evaluate()`` runs inline (the background thread is
optional and owns no decision logic), and drains use an injectable
sleep. Observability: ``app_tpu_scale_events_total{direction}`` and the
pool's ``app_tpu_pool_replicas{state}`` gauge refresh every sweep.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Optional

from gofr_tpu.service.replica_pool import Replica, ReplicaPool


class PoolScaler:
    """Watches a :class:`ReplicaPool`'s load signals and spawns/drains
    replicas through injectable callbacks. See the module docstring for
    the decision rule."""

    def __init__(
        self,
        pool: ReplicaPool,
        spawn: Callable[[], Replica],
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        up_load_per_replica: float = 4.0,
        down_load_per_replica: float = 0.5,
        up_headroom_floor: float = 0.0,
        up_on_brownout: bool = True,
        up_on_control: bool = True,
        scale_up_wait_s: float = 10.0,
        scale_down_wait_s: float = 60.0,
        drain_timeout_s: float = 30.0,
        interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.pool = pool
        self.spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_load_per_replica = float(up_load_per_replica)
        self.down_load_per_replica = float(down_load_per_replica)
        # Saturation-aware scale-up (TPU_SCALE_UP_HEADROOM, 0 = off):
        # a serving replica whose HBM headroom ratio sits below this
        # floor counts as pressure even when its queue looks shallow —
        # a nearly-full paged pool sheds/fails work the queue-depth
        # signal never sees coming (device_telemetry's headroom is the
        # same signal admission and the eviction watermark read).
        self.up_headroom_floor = float(up_headroom_floor)
        # Brownout-aware scale-up (TPU_SCALE_UP_BROWNOUT, default on):
        # a serving replica holding brownout level 2+ is deliberately
        # shedding admissions — demand the queue-depth signal no longer
        # sees. Sustained through the same scale_up_wait_s window, so a
        # short burn spike spawns nothing.
        self.up_on_brownout = bool(up_on_brownout)
        # Control-plane-aware scale-up (TPU_SCALE_UP_CONTROL, default
        # on): a serving replica whose control plane asserts pressure
        # (sustained host-overhead saturation, or the predictive
        # queue-trend fit projecting a breach) counts as pressure —
        # the predictive loop is what lets the pool spawn BEFORE the
        # reactive sustained-threshold signals trip.
        self.up_on_control = bool(up_on_control)
        self.scale_up_wait_s = float(scale_up_wait_s)
        self.scale_down_wait_s = float(scale_down_wait_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.interval_s = float(interval_s)
        self._clock = clock
        self._sleep = sleep
        self._metrics = metrics
        self._logger = logger
        # Sustain-window anchors: the first sweep that saw pressure
        # (resp. idleness) continuously holding since.
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Replicas THIS scaler spawned, preferred for retirement: the
        # operator's hand-configured replicas are the floor fleet.
        self._spawned: list[Replica] = []

    # -- signals -----------------------------------------------------------

    def _capacity(self) -> list[Replica]:
        """Replicas currently counting as capacity: routable and not
        leaving."""
        return [
            r for r in self.pool.replicas
            if not r.draining
            and not r.probe_failed
            and r.state() in ("SERVING", "DEGRADED")
        ]

    def _min_headroom(self, capacity: list[Replica]) -> Optional[float]:
        """The worst advertised HBM headroom across serving capacity
        when it violates the floor, else None. None-advertising
        replicas (remotes before their first probe) don't count —
        absence of the signal must not read as pressure."""
        if self.up_headroom_floor <= 0:
            return None
        # A non-finite advertisement (a remote echoing NaN telemetry)
        # is a lying sensor, not pressure — same as None (ISSUE 17
        # threshold-wiring audit).
        ratios = [
            h for r in capacity for h in (r.headroom(),)
            if h is not None and math.isfinite(h)
        ]
        if not ratios:
            return None
        worst = min(ratios)
        return worst if worst < self.up_headroom_floor else None

    def _max_brownout(self, capacity: list[Replica]) -> Optional[int]:
        """The worst advertised brownout level across serving capacity
        when it reaches the admission-shedding rungs (L2+), else None.
        None-advertising replicas don't count — absence of the signal
        must not read as pressure."""
        if not self.up_on_brownout:
            return None
        levels = [
            lvl for r in capacity
            for lvl in (r.brownout_level(),) if lvl is not None
        ]
        if not levels:
            return None
        worst = max(levels)
        return worst if worst >= 2 else None

    def _max_control(self, capacity: list[Replica]) -> Optional[int]:
        """1 when any serving replica's control plane asserts scale-up
        pressure (host-overhead or predictive loop), else None.
        None-advertising replicas (plane off, remotes before their
        first probe) don't count — absence of the signal must not read
        as pressure."""
        if not self.up_on_control:
            return None
        flags = [
            p for r in capacity
            for p in (r.control_pressure(),) if p is not None
        ]
        if not flags:
            return None
        worst = max(flags)
        return worst if worst >= 1 else None

    def load_per_replica(self) -> float:
        """Aggregate outstanding work over serving capacity — the
        scaling signal. Work queued while NO capacity serves reads as
        infinite pressure (spawn immediately)."""
        capacity = self._capacity()
        total = sum(r.load() for r in capacity)
        # Draining replicas still hold in-flight work but their load is
        # leaving the pool with them; it is not future demand.
        if not capacity:
            return float("inf")
        return total / len(capacity)

    # -- one sweep ---------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> str:
        """One scaling decision; returns ``"up"``, ``"down"``, or
        ``"steady"``. The background thread calls this on its interval;
        tests call it directly with a stated clock."""
        now = self._clock() if now is None else now
        capacity = self._capacity()
        n = len(capacity)
        load = self.load_per_replica()

        # Floor repair outranks the sustain windows: below min the pool
        # is in violation NOW (replicas died or an operator drained too
        # far), not merely under pressure.
        if n < self.min_replicas:
            return self._scale_up(now, reason="below min_replicas")

        low_headroom = self._min_headroom(capacity)
        hot_brownout = self._max_brownout(capacity)
        hot_control = self._max_control(capacity)
        if (
            load > self.up_load_per_replica
            or low_headroom is not None
            or hot_brownout is not None
            or hot_control is not None
        ):
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            if (
                now - self._pressure_since >= self.scale_up_wait_s
                and n < self.max_replicas
            ):
                reason = (
                    f"load/replica {load:.1f} > "
                    f"{self.up_load_per_replica:.1f} for "
                    f"{self.scale_up_wait_s:.0f}s"
                )
                if low_headroom is not None:
                    reason = (
                        f"HBM headroom {low_headroom:.3f} < "
                        f"{self.up_headroom_floor:.3f} for "
                        f"{self.scale_up_wait_s:.0f}s"
                    )
                elif hot_brownout is not None:
                    reason = (
                        f"brownout level {hot_brownout} (L2+ sheds "
                        f"admissions) for {self.scale_up_wait_s:.0f}s"
                    )
                elif hot_control is not None:
                    reason = (
                        f"control-plane scale pressure (host-overhead/"
                        f"predictive loop) for {self.scale_up_wait_s:.0f}s"
                    )
                return self._scale_up(now, reason=reason)
            return "steady"

        self._pressure_since = None
        if load < self.down_load_per_replica and n > self.min_replicas:
            if self._idle_since is None:
                self._idle_since = now
            if now - self._idle_since >= self.scale_down_wait_s:
                return self._scale_down(now)
            return "steady"

        self._idle_since = None
        return "steady"

    def _scale_up(self, now: float, reason: str) -> str:
        if len(self.pool.replicas) >= self.max_replicas:
            # Membership (not just capacity) is at the ceiling: respawn
            # nothing — recovery of the existing DOWN replicas is the
            # prober's job, and exceeding TPU_POOL_MAX_REPLICAS is never
            # allowed, even to repair the floor.
            return "steady"
        try:
            replica = self.spawn()
        except Exception as exc:  # noqa: BLE001 — a failed spawn must not kill the sweep
            if self._logger is not None:
                self._logger.errorf("replica spawn failed: %s", exc)
            return "steady"
        self.pool.add_replica(replica)
        self._spawned.append(replica)
        self._pressure_since = None
        self._idle_since = None
        self._count("up")
        if self._logger is not None:
            self._logger.infof(
                "scaled up: replica %s joined (%s); pool now %d",
                replica.name, reason, len(self.pool.replicas),
            )
        return "up"

    def _scale_down(self, now: float) -> str:
        victim = self._pick_victim()
        if victim is None:
            return "steady"
        drained = self.pool.drain_replica(
            victim,
            timeout_s=self.drain_timeout_s,
            sleep=self._sleep,
        )
        if not drained:
            # Bounded drain could not empty the replica: it re-entered
            # routing, nothing was dropped; keep the idle anchor so the
            # next sweep retries without restarting the sustain window.
            return "steady"
        if victim in self._spawned:
            self._spawned.remove(victim)
        self._idle_since = None
        self._count("down")
        if self._logger is not None:
            self._logger.infof(
                "scaled down: replica %s drained and retired; pool now "
                "%d", victim.name, len(self.pool.replicas),
            )
        return "down"

    def _pick_victim(self) -> Optional[Replica]:
        """Idlest scaler-spawned replica first; never the last
        ``min_replicas`` of capacity."""
        capacity = self._capacity()
        if len(capacity) <= self.min_replicas:
            return None
        spawned = [r for r in capacity if r in self._spawned]
        candidates = spawned or capacity
        return min(candidates, key=lambda r: r.load())

    def _count(self, direction: str) -> None:
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_tpu_scale_events_total", "direction", direction
            )
        self.pool.publish_pool_gauges()

    # -- background loop ---------------------------------------------------

    def start(self) -> "PoolScaler":
        if self.interval_s <= 0:
            return self
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu-pool-scaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as exc:  # noqa: BLE001 — the scaler must survive
                if self._logger is not None:
                    self._logger.errorf("pool scaler sweep failed: %s", exc)

    def describe(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "replicas": len(self.pool.replicas),
            "load_per_replica": (
                -1.0 if self.load_per_replica() == float("inf")
                else round(self.load_per_replica(), 3)
            ),
            "up_load_per_replica": self.up_load_per_replica,
            "down_load_per_replica": self.down_load_per_replica,
            "up_headroom_floor": self.up_headroom_floor,
            "up_on_brownout": self.up_on_brownout,
            "up_on_control": self.up_on_control,
            "scale_up_wait_s": self.scale_up_wait_s,
            "scale_down_wait_s": self.scale_down_wait_s,
            "spawned": [r.name for r in self._spawned],
        }
