"""Cross-process KV-block transfer server — the ``dma`` leg's backend.

The transfer-leg ladder (``service/replica_pool.py``, PR 13) tops out
at single-process moves: the ``device`` leg needs a shared JAX runtime
and the ``wire`` leg ships every plane byte through an HTTP POST. This
module adds the missing top rung: the exporter STAGES a payload once
and hands the importer a tiny claim ticket (:class:`~gofr_tpu.ops.\
kv_cache.KVHandlePayload`); the importer redeems it with a direct
socket fetch from the exporting process — the jax-transfer-server
shape, where control (the ops-port POST) and data (the block bytes)
travel different paths and the data path is point-to-point.

Two backends share this seam:

* **ICI/DMA (real TPU pods)** — ``jax.experimental.transfer``'s
  cross-host transfer server, when the installed jax provides it
  (:func:`jax_transfer_available`). There the staged entry would be
  device buffers and the fetch an ICI pull that never touches host
  memory.
* **Loopback emulation (CI, CPU)** — a thread-per-connection TCP
  server over the payload's wire bytes. Same handles, same staging
  TTL, same failure modes (connect-refused, mid-read reset, stale
  key, checksum mismatch), so the WHOLE failure matrix runs on a
  laptop: chaos tests ``kill -9`` a real exporting process mid-fetch
  and watch the ladder descend one rung.

Failure currency is :class:`DmaError` with ``kind`` ∈
``connect`` / ``read`` / ``stale`` / ``proto`` — the replica pool maps
any of them to "ban the dma rung for this attempt and retry the same
target one rung down", mirroring how ``ErrorServiceUnavailable.kind``
drives the wire leg's matrix.

Fault points (armed by tests, fired unconditionally):

* ``transfer.dma.offer`` — before a payload is staged (raise = the
  transfer server refusing/unreachable at export time);
* ``transfer.dma.fetch`` — in :func:`dma_fetch` before the socket
  opens (raise = connect-refused/reset without a socket);
* ``transfer.dma.serve`` — server side, after the key is read and
  before the reply frame (an ``action`` that blocks models a stalled
  exporter: the importer's read budget, not patience, decides).

Determinism: the server holds no timers beyond the staging TTL (an
injectable clock); "slow" is modeled by armed blocking actions or —
in the subprocess chaos suite — by a genuinely killed process, with
every wait bounded by explicit connect/read budgets (GL024 pins that
no fetch call site may omit them).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional

from gofr_tpu import faults
from gofr_tpu.ops.kv_cache import (
    KVBlockPayload,
    KVHandlePayload,
    payload_from_wire,
    payload_to_wire,
)

if TYPE_CHECKING:
    from gofr_tpu.serving.lifecycle import Deadline

#: Fetch-protocol magic: client sends ``KVD1`` + u16 key length + key;
#: server replies ``KVD1`` + u64 body length + wire bytes. Length 0 =
#: unknown/expired key — the STALE HANDLE frame, distinct from a dead
#: socket so the importer can tell "exporter forgot" from "exporter
#: died".
FETCH_MAGIC = b"KVD1"

#: Default staging TTL: a handle outliving its transfer attempt by this
#: much is garbage — the exporter already degraded to another rung, so
#: holding the host copy longer only pins memory.
DEFAULT_TTL_S = 120.0

#: Per-read socket chunk. Small enough that a mid-transfer kill lands
#: between reads (the chaos suite's kill -9 cell), large enough that a
#: multi-MB payload costs few syscalls.
_CHUNK = 1 << 16


class DmaError(Exception):
    """A dma-leg transfer failure, tagged with how it failed.

    ``kind``:

    * ``connect`` — the exporter's data port is unreachable (process
      dead, port refused, connect budget exceeded): the TARGET of the
      handle is gone, not just this attempt;
    * ``read``    — the socket opened but the body never finished
      inside the read budget (mid-transfer kill, partition, slow-loris
      stall);
    * ``stale``   — the exporter answered but disowned the key (TTL
      expiry, restart) or the fetched bytes contradict the handle's
      checksum/geometry;
    * ``proto``   — framing violation (wrong magic, truncated header):
      version drift between pods.
    """

    def __init__(self, message: str, *, kind: str) -> None:
        super().__init__(message)
        self.kind = kind


def jax_transfer_available() -> bool:
    """Whether the installed jax carries the cross-host transfer-server
    API (``jax.experimental.transfer``, jax ≥ 0.5). On the CI jax it
    does not — the loopback emulation below is then the only backend,
    which is exactly what makes the failure matrix runnable without a
    pod."""
    try:
        import jax.experimental.transfer  # noqa: F401
    except ImportError:
        return False
    return True


@dataclass
class _Staged:
    body: bytes
    expires_at: float
    src: str = ""


class DmaTransferServer:
    """Loopback transfer server: stages wire-serialized payloads under
    single-use keys and serves them over a raw TCP fetch protocol.

    One instance per process (module-level :func:`get_transfer_server`)
    — every export in the process stages here, every importer fetch
    lands here, and the chaos suite killing the process severs ALL its
    outstanding handles at once, exactly like a dead pod.

    Thread model: ``start()`` spawns one daemon accept thread plus one
    daemon thread per connection; ``offer``/``redeem`` are called from
    scheduler/pool threads under ``_lock``. Nothing here touches
    device memory — staged bodies are the host-bounce payload's wire
    bytes, so the server is safe to run beside donated cache planes.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        ttl_s: float = DEFAULT_TTL_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._host = host
        self._port = int(port)
        self._ttl_s = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._staged: dict[str, _Staged] = {}
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self.fetches_served = 0  # observability only; under _lock

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "DmaTransferServer":
        if self._sock is not None:
            return self
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._port))
        sock.listen(16)
        self._sock = sock
        self._port = int(sock.getsockname()[1])
        self._stopping.clear()
        thread = threading.Thread(
            target=self._accept_loop, name="dma-transfer-server", daemon=True
        )
        self._accept_thread = thread
        thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        thread, self._accept_thread = self._accept_thread, None
        if thread is not None:
            thread.join(timeout=2.0)
        with self._lock:
            self._staged.clear()

    @property
    def address(self) -> str:
        """``host:port`` as handles advertise it (valid after start)."""
        return f"{self._host}:{self._port}"

    @property
    def running(self) -> bool:
        return self._sock is not None

    # -- export side ---------------------------------------------------

    def offer(self, payload: KVBlockPayload, *, src: str = "") -> KVHandlePayload:
        """Stage ``payload``'s wire bytes and mint the claim ticket the
        importer redeems. Expired siblings are swept on every offer —
        the staging dict is bounded by (in-flight transfers × TTL),
        never by traffic history."""
        if self._sock is None:
            raise DmaError(
                "transfer server not running; dma leg unavailable",
                kind="connect",
            )
        faults.fire("transfer.dma.offer", src=src, server=self.address)
        body = payload_to_wire(payload)
        key = uuid.uuid4().hex
        now = self._clock()
        with self._lock:
            for stale in [
                k for k, s in self._staged.items() if s.expires_at <= now
            ]:
                del self._staged[stale]
            self._staged[key] = _Staged(
                body=body, expires_at=now + self._ttl_s, src=src
            )
        return KVHandlePayload(
            address=self.address,
            key=key,
            block=payload.block,
            token_ids=payload.token_ids,
            src=src or payload.src,
            checksum=payload.checksum,
            geometry=payload.geometry,
            nbytes_hint=len(body),
        )

    def redeem(self, key: str) -> Optional[bytes]:
        """Single-use claim: pop the staged body (None = stale/unknown).
        Single-use is deliberate — a handle replayed after its transfer
        settled must read as stale, not re-ship blocks whose radix
        entries may since have been evicted."""
        now = self._clock()
        with self._lock:
            staged = self._staged.pop(key, None)
            if staged is not None and staged.expires_at > now:
                self.fetches_served += 1
                return staged.body
        return None

    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)

    # -- serve side ----------------------------------------------------

    def _accept_loop(self) -> None:
        sock = self._sock
        while sock is not None and not self._stopping.is_set():
            try:
                conn, _ = sock.accept()
            except OSError:
                return  # closed under us: normal stop path
            threading.Thread(
                target=self._serve_one, args=(conn,), daemon=True
            ).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(10.0)  # a client that never asks can't pin the thread
                head = _read_exact(conn, len(FETCH_MAGIC) + 2)
                if head is None or head[:4] != FETCH_MAGIC:
                    return  # protocol garbage: drop, importer sees a reset
                (key_len,) = struct.unpack(">H", head[4:6])
                raw_key = _read_exact(conn, key_len)
                if raw_key is None:
                    return
                key = raw_key.decode("ascii", errors="replace")
                # Chaos seam: a blocking action here is a stalled
                # exporter mid-transfer — the importer's read budget
                # must cut the wait, and kill -9 during the stall is
                # the "died mid-DMA" matrix cell.
                faults.fire("transfer.dma.serve", key=key, server=self.address)
                body = self.redeem(key)
                if body is None:
                    conn.sendall(FETCH_MAGIC + struct.pack(">Q", 0))
                    return
                conn.sendall(FETCH_MAGIC + struct.pack(">Q", len(body)))
                for off in range(0, len(body), _CHUNK):
                    conn.sendall(body[off:off + _CHUNK])
        except OSError:
            return  # importer vanished mid-send: its problem, not ours


def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def dma_fetch(
    handle: KVHandlePayload,
    *,
    deadline: "Optional[Deadline]" = None,
    connect_timeout_s: float = 2.0,
    read_timeout_s: float = 10.0,
) -> KVBlockPayload:
    """Redeem ``handle`` against its exporter's transfer server and
    return the verified inline payload.

    Budgets are mandatory and layered: ``connect_timeout_s`` bounds the
    handshake (a dead pod answers in one RTT, not a read timeout),
    ``read_timeout_s`` bounds EVERY individual socket read (a stalled
    exporter — slow-loris, partition mid-body — trips it), and a
    request ``deadline`` (``serving.lifecycle.Deadline``) clamps both
    so a transfer never outlives the request it serves. Raises
    :class:`DmaError`; never returns a payload whose bytes contradict
    the handle's checksum or geometry."""
    remaining: Optional[float] = None
    if deadline is not None:
        remaining = float(deadline.remaining())
        if remaining <= 0:
            raise DmaError("deadline expired before dma fetch", kind="read")
    connect_budget = (
        connect_timeout_s if remaining is None
        else max(1e-3, min(connect_timeout_s, remaining))
    )
    read_budget = (
        read_timeout_s if remaining is None
        else max(1e-3, min(read_timeout_s, remaining))
    )
    # Chaos seam: connect-refused / reset without a socket in sight.
    faults.fire("transfer.dma.fetch", key=handle.key, address=handle.address)
    host, _, port_str = handle.address.rpartition(":")
    try:
        port = int(port_str)
    except ValueError:
        raise DmaError(
            f"handle address {handle.address!r} is not host:port",
            kind="proto",
        ) from None
    try:
        conn = socket.create_connection((host, port), timeout=connect_budget)
    except (OSError, socket.timeout) as exc:
        raise DmaError(
            f"dma connect to {handle.address} failed: {exc}", kind="connect"
        ) from exc
    try:
        with conn:
            conn.settimeout(read_budget)
            raw_key = handle.key.encode("ascii")
            conn.sendall(
                FETCH_MAGIC + struct.pack(">H", len(raw_key)) + raw_key
            )
            head = _fetch_exact(conn, 12, handle.address)
            if head[:4] != FETCH_MAGIC:
                raise DmaError(
                    f"dma reply from {handle.address} has wrong magic",
                    kind="proto",
                )
            (nbytes,) = struct.unpack(">Q", head[4:12])
            if nbytes == 0:
                raise DmaError(
                    f"handle {handle.key[:8]}… is stale on {handle.address}",
                    kind="stale",
                )
            body = _fetch_exact(conn, int(nbytes), handle.address)
    except socket.timeout as exc:
        raise DmaError(
            f"dma read from {handle.address} exceeded its "
            f"{read_budget:.3f}s budget", kind="read",
        ) from exc
    except OSError as exc:
        raise DmaError(
            f"dma read from {handle.address} failed: {exc}", kind="read"
        ) from exc
    try:
        payload = payload_from_wire(body)
    except ValueError as exc:
        raise DmaError(
            f"dma body from {handle.address} undecodable: {exc}",
            kind="stale",
        ) from exc
    # The fetched bytes must be the bytes the handle promised — a
    # transfer server restarted into a new staging namespace (or a
    # mismatched redeem) reads as a stale handle, never as an aliasable
    # payload. Geometry drift across pods is also caught right here,
    # before the importer touches its pool.
    if (
        payload.checksum != handle.checksum
        or tuple(payload.geometry) != tuple(handle.geometry)
        or payload.token_ids != handle.token_ids
        or not payload.verify()
    ):
        raise DmaError(
            f"dma body from {handle.address} contradicts its handle "
            f"(checksum/geometry/token drift)", kind="stale",
        )
    return payload


def _fetch_exact(conn: socket.socket, n: int, address: str) -> bytes:
    """Bounded exact read: the per-read socket timeout set by the
    caller applies to every ``recv``; a clean EOF short of ``n`` is a
    mid-transfer death (kind=read)."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(min(_CHUNK, n - len(buf)))
        if not chunk:
            raise DmaError(
                f"dma stream from {address} ended {n - len(buf)} bytes "
                f"early (exporter died mid-transfer?)", kind="read",
            )
        buf += chunk
    return buf


# ----------------------------------------------------------------------
# Process-wide server (one data port per process, like one ops port)
# ----------------------------------------------------------------------

_process_server: Optional[DmaTransferServer] = None
_process_lock = threading.Lock()


def get_transfer_server(*, start: bool = True) -> DmaTransferServer:
    """The process's shared transfer server, lazily bound on first use
    (``TPU_DMA_HOST`` / ``TPU_DMA_PORT`` / ``TPU_DMA_TTL_S`` override
    the loopback defaults). Every exporter in the process stages here;
    the address travels inside each handle, so importers never need the
    configuration — killing this process severs every handle it minted,
    which is the point."""
    global _process_server
    with _process_lock:
        if _process_server is None:
            _process_server = DmaTransferServer(
                host=os.environ.get("TPU_DMA_HOST", "127.0.0.1"),
                port=int(os.environ.get("TPU_DMA_PORT", "0")),
                ttl_s=float(os.environ.get("TPU_DMA_TTL_S", str(DEFAULT_TTL_S))),
            )
        server = _process_server
    if start and not server.running:
        server.start()
    return server


def reset_transfer_server() -> None:
    """Test hook: stop and forget the process server (next
    :func:`get_transfer_server` binds a fresh port — old handles all
    read as connect-refused or stale, exactly like a pod restart)."""
    global _process_server
    with _process_lock:
        server, _process_server = _process_server, None
    if server is not None:
        server.stop()
