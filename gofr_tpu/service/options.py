"""Service client option decorators (reference ``service/options.go:3-5`` +
the per-option files: ``apikey_auth.go``, ``basic_auth.go``, ``oauth.go``,
``health_config.go``, ``default_headers.go``)."""

from __future__ import annotations

import base64
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Optional

from gofr_tpu.service.wrapper import ServiceWrapper, innermost


class _HeaderInjector(ServiceWrapper):
    """Shared shape: wraps a service and injects headers per request."""

    def _headers(self) -> dict:
        return {}

    def request(self, method: str, path: str, *, headers: Any = None, **kw: Any) -> Any:
        merged = {**self._headers(), **(headers or {})}
        return self._inner.request(method, path, headers=merged, **kw)


@dataclass
class APIKeyConfig:
    """X-API-KEY header on every call (reference ``service/apikey_auth.go``)."""

    api_key: str

    def add_option(self, svc: Any) -> Any:
        cfg = self

        class _Svc(_HeaderInjector):
            def _headers(self) -> dict:
                return {"X-API-KEY": cfg.api_key}

        return _Svc(svc)


@dataclass
class BasicAuthConfig:
    """Authorization: Basic (reference ``service/basic_auth.go``)."""

    username: str
    password: str

    def add_option(self, svc: Any) -> Any:
        token = base64.b64encode(
            f"{self.username}:{self.password}".encode()
        ).decode()

        class _Svc(_HeaderInjector):
            def _headers(self) -> dict:
                return {"Authorization": f"Basic {token}"}

        return _Svc(svc)


@dataclass
class OAuthConfig:
    """Client-credentials bearer token with caching + refresh
    (reference ``service/oauth.go:15-33``)."""

    token_url: str
    client_id: str
    client_secret: str
    scopes: tuple = ()
    _cache: dict = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _token(self) -> str:
        with self._lock:
            tok = self._cache.get("token")
            if tok and time.time() < self._cache.get("expiry", 0) - 30:
                return tok
            import json as jsonlib
            import urllib.parse
            import urllib.request

            data = urllib.parse.urlencode({
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                **({"scope": " ".join(self.scopes)} if self.scopes else {}),
            }).encode()
            # Single-flight by design: the lock held across the fetch is
            # what stops N threads with an expired token from minting N
            # tokens; waiters get the fresh token from the cache. The
            # urlopen timeout bounds the convoy.
            with urllib.request.urlopen(  # graftlint: disable=GL022 — single-flight token refresh; bounded by timeout=10
                urllib.request.Request(self.token_url, data=data), timeout=10
            ) as resp:
                payload = jsonlib.loads(resp.read())
            self._cache["token"] = payload["access_token"]
            self._cache["expiry"] = time.time() + float(payload.get("expires_in", 3600))
            return self._cache["token"]

    def add_option(self, svc: Any) -> Any:
        cfg = self

        class _Svc(_HeaderInjector):
            def _headers(self) -> dict:
                return {"Authorization": f"Bearer {cfg._token()}"}

        return _Svc(svc)


@dataclass
class DefaultHeaders:
    headers: Mapping[str, str]

    def add_option(self, svc: Any) -> Any:
        cfg = self

        class _Svc(_HeaderInjector):
            def _headers(self) -> dict:
                return dict(cfg.headers)

        return _Svc(svc)


@dataclass
class HealthConfig:
    """Override the health endpoint (reference ``service/health_config.go``)."""

    endpoint: str = ".well-known/alive"

    def add_option(self, svc: Any) -> Any:
        # health_check() runs on the base client regardless of wrapping
        # order, so the override must land on the innermost service — not
        # on whatever wrapper happens to be outermost.
        innermost(svc).health_endpoint = self.endpoint.lstrip("/")
        return svc


@dataclass
class RetryConfig:
    """Retry 5xx / connection errors with JITTERED exponential backoff
    (net-new; the reference leaves retries to the caller).

    Fixed-delay retries synchronize thundering herds: every client that
    failed at t₀ retries at exactly t₀+d, re-spiking the service it just
    knocked over. Each delay is therefore the exponential base
    ``backoff_s · 2^attempt`` (capped at ``max_backoff_s``) scaled by a
    uniform draw from ``[1 - jitter, 1 + jitter]`` — clients decorrelate
    while the expected delay stays the configured schedule. ``rng`` is
    injectable so tests pin the draw (``docs/advanced-guide/
    http-communication.md``).
    """

    max_retries: int = 3
    backoff_s: float = 0.1
    jitter: float = 0.5  # ±50% of the exponential base
    max_backoff_s: float = 30.0
    rng: Callable[[], float] = field(default=random.random)

    def delay_s(self, attempt: int) -> float:
        """The jittered sleep before retry ``attempt + 1`` (attempt is
        0-based). Bounds: base·(1-jitter) ≤ delay ≤ base·(1+jitter)."""
        base = min(self.backoff_s * (2 ** attempt), self.max_backoff_s)
        jitter = min(max(self.jitter, 0.0), 1.0)
        factor = 1.0 - jitter + 2.0 * jitter * self.rng()
        return base * factor

    def add_option(self, svc: Any) -> Any:
        cfg = self

        class _Svc(_HeaderInjector):
            def request(self, method: str, path: str, **kw: Any) -> Any:
                last_exc: Optional[Exception] = None
                for attempt in range(cfg.max_retries + 1):
                    try:
                        resp = self._inner.request(method, path, **kw)
                        if resp.status_code < 500 or attempt == cfg.max_retries:
                            return resp
                    except Exception as exc:  # connection errors
                        last_exc = exc
                        if attempt == cfg.max_retries:
                            raise
                    time.sleep(cfg.delay_s(attempt))
                if last_exc is not None:
                    raise last_exc
                return resp

        return _Svc(svc)
