"""Inter-service HTTP client (reference: ``pkg/gofr/service``, ~1,061 LoC).

Decorator-pattern client: ``new_http_service(addr, logger, metrics,
*options)`` folds ``Option`` wrappers over a base client (reference
``service/new.go:68-87``, ``service/options.go:3-5``). Options: circuit
breaker, health config, retries, API-key/basic/OAuth auth, default headers.
"""

from gofr_tpu.service.client import HTTPService, Response, new_http_service
from gofr_tpu.service.circuit_breaker import (
    CircuitBreakerConfig,
    CircuitOpenError,
)
from gofr_tpu.service.options import (
    APIKeyConfig,
    BasicAuthConfig,
    DefaultHeaders,
    HealthConfig,
    OAuthConfig,
    RetryConfig,
)
from gofr_tpu.service.pool_scaler import PoolScaler
from gofr_tpu.service.replica_pool import (
    EngineReplica,
    HTTPReplica,
    Replica,
    ReplicaPool,
)

__all__ = [
    "PoolScaler",
    "HTTPService",
    "Response",
    "new_http_service",
    "CircuitBreakerConfig",
    "CircuitOpenError",
    "APIKeyConfig",
    "BasicAuthConfig",
    "OAuthConfig",
    "DefaultHeaders",
    "HealthConfig",
    "RetryConfig",
    "Replica",
    "EngineReplica",
    "HTTPReplica",
    "ReplicaPool",
]
