"""Dotenv-layered env config.

Behavioral contract (from the reference, re-implemented from scratch):

* ``Config`` is a two-method seam — ``get`` / ``get_or_default``
  (reference ``config/config.go:3-6``).
* ``EnvLoader`` reads ``<dir>/.env`` into the process environment, then
  overlays ``<dir>/.<APP_ENV>.env`` when ``APP_ENV`` is set, else
  ``<dir>/.local.env`` when present; overlay files *override* earlier values
  (reference ``config/godotenv.go:32-67``). Reads always come from the live
  process env so externally-set variables win at lookup time
  (reference ``config/godotenv.go:69-79``).
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional, Protocol


class Config(Protocol):
    """Two-method config seam (reference ``config/config.go:3-6``)."""

    def get(self, key: str) -> Optional[str]: ...

    def get_or_default(self, key: str, default: str) -> str: ...


def _parse_dotenv(path: str) -> dict[str, str]:
    """Parse a dotenv file: KEY=VALUE lines, '#' comments, optional quotes."""
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as fp:
            for raw in fp:
                line = raw.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                if line.startswith("export "):
                    line = line[len("export ") :]
                key, _, value = line.partition("=")
                key = key.strip()
                value = value.strip()
                # Strip one matching layer of quotes.
                if len(value) >= 2 and value[0] == value[-1] and value[0] in "'\"":
                    value = value[1:-1]
                else:
                    # Trailing inline comment (only outside quotes).
                    if " #" in value:
                        value = value.split(" #", 1)[0].rstrip()
                if key:
                    out[key] = value
    except FileNotFoundError:
        pass
    return out


class EnvLoader:
    """Loads dotenv files into ``os.environ`` and reads keys from it."""

    def __init__(self, config_dir: str, logger: Any = None) -> None:
        self._dir = config_dir
        self._logger = logger
        self._read()

    def _read(self) -> None:
        base = os.path.join(self._dir, ".env")
        base_vals = _parse_dotenv(base)
        # Base file must not override already-exported process env
        # (godotenv.Load semantics, reference config/godotenv.go:41).
        loaded = False
        for k, v in base_vals.items():
            loaded = True
            os.environ.setdefault(k, v)

        app_env = os.environ.get("APP_ENV", "")
        if app_env:
            overlay = os.path.join(self._dir, f".{app_env}.env")
        else:
            overlay = os.path.join(self._dir, ".local.env")
        overlay_vals = _parse_dotenv(overlay)
        # Overlay files DO override (godotenv.Overload semantics,
        # reference config/godotenv.go:50-63).
        for k, v in overlay_vals.items():
            loaded = True
            os.environ[k] = v

        if self._logger is not None:
            if overlay_vals:
                self._logger.info(f"Loaded config from {base} overlaid by {overlay}")
            elif loaded:
                self._logger.info(f"Loaded config from {base}")

    def get(self, key: str) -> Optional[str]:
        return os.environ.get(key)

    def get_or_default(self, key: str, default: str) -> str:
        val = os.environ.get(key)
        if val is None or val == "":
            return default
        return val


def new_env_file(config_dir: str, logger: Any = None) -> EnvLoader:
    """Factory mirroring the reference's ``config.NewEnvFile`` (``config/godotenv.go:25``)."""
    return EnvLoader(config_dir, logger)


class MockConfig:
    """Static map config for tests (reference ``config/mock_config.go:6-12``)."""

    def __init__(self, values: Mapping[str, str] | None = None) -> None:
        self._values = dict(values or {})

    def get(self, key: str) -> Optional[str]:
        return self._values.get(key)

    def get_or_default(self, key: str, default: str) -> str:
        val = self._values.get(key)
        if val is None or val == "":
            return default
        return val
