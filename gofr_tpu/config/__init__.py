"""Configuration layer (reference: ``pkg/gofr/config``).

Env-var-first configuration with dotenv layering, mirroring the reference's
``config/config.go:3-6`` (two-method interface) and ``config/godotenv.go:25-79``
(``configs/.env`` loaded first, then overlaid by ``.local.env`` or
``.${APP_ENV}.env``).
"""

from gofr_tpu.config.env import Config, EnvLoader, MockConfig, new_env_file

__all__ = ["Config", "EnvLoader", "MockConfig", "new_env_file"]
