"""Subscription manager (reference ``pkg/gofr/subscriber.go:13-84``).

One async task per subscribed topic, each looping: poll the broker (in a
worker thread, since broker clients block), wrap the message as the request
in a fresh Context, run the handler with panic recovery, and commit only on
success (reference ``subscriber.go:27-57,63-84``). Errors log-and-continue;
cancellation stops the loop (the graceful-shutdown hook the reference lacks).
"""

from __future__ import annotations

import asyncio
import traceback
from typing import Callable

from gofr_tpu.context import Context


class SubscriptionManager:
    def __init__(self, container) -> None:
        self._container = container
        self._subscriptions: dict[str, Callable] = {}
        self._tasks: list[asyncio.Task] = []

    def register(self, topic: str, handler: Callable) -> None:
        self._subscriptions[topic] = handler

    @property
    def topics(self) -> list[str]:
        return list(self._subscriptions)

    def start(self) -> None:
        for topic, handler in self._subscriptions.items():
            self._tasks.append(
                asyncio.get_running_loop().create_task(
                    self._run_loop(topic, handler), name=f"subscriber-{topic}"
                )
            )

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    async def _run_loop(self, topic: str, handler) -> None:
        container = self._container
        logger = container.logger
        loop = asyncio.get_running_loop()
        is_async = asyncio.iscoroutinefunction(handler)
        while True:
            subscriber = container.get_subscriber()
            if subscriber is None:
                await asyncio.sleep(1.0)
                continue
            try:
                msg = await loop.run_in_executor(None, subscriber.subscribe, topic, 0.5)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                logger.errorf("error while reading from topic %s: %s", topic, exc)
                await asyncio.sleep(0.1)  # the reference hot-loops here; back off instead
                continue
            if msg is None:
                continue
            ctx = Context(request=msg, container=container)
            try:
                if is_async:
                    err = await handler(ctx)
                else:
                    err = await loop.run_in_executor(None, handler, ctx)
            except asyncio.CancelledError:
                raise
            except Exception:
                # Panic recovery (reference subscriber.go:63-84).
                logger.errorf(
                    "subscriber handler for topic %s panicked:\n%s",
                    topic,
                    traceback.format_exc(),
                )
                continue
            if err is None or err is True:
                msg.commit()
