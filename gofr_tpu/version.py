"""Framework version constant (reference: ``pkg/gofr/version/version.go:3``)."""

FRAMEWORK_VERSION = "0.1.0-dev"
