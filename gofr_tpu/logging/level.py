"""Log levels (reference ``logging/level.go:8-17,52-66``)."""

from __future__ import annotations

import enum


class Level(enum.IntEnum):
    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    FATAL = 6

    @property
    def color(self) -> int:
        """ANSI 256-color code for terminal pretty printing
        (reference ``logging/level.go:33-50``)."""
        return {
            Level.DEBUG: 7,  # light grey
            Level.INFO: 6,  # cyan
            Level.NOTICE: 6,
            Level.WARN: 3,  # yellow
            Level.ERROR: 160,  # red
            Level.FATAL: 160,
        }[self]


def level_from_string(s: str | None, default: Level = Level.INFO) -> Level:
    """Parse LOG_LEVEL-style strings case-insensitively
    (reference ``logging/level.go:52-66``)."""
    if not s:
        return default
    try:
        return Level[s.strip().upper()]
    except KeyError:
        return default
