"""Structured leveled logging (reference: ``pkg/gofr/logging``).

Leveled JSON logger with terminal pretty-printing, stdout/stderr split at
ERROR, file logger for CLI apps, and hot-swappable level — the capability set
of the reference's ``logging/logger.go`` + ``logging/dynamicLevelLogger.go``.
"""

from gofr_tpu.logging.level import Level, level_from_string
from gofr_tpu.logging.logger import (
    Logger,
    PrettyPrint,
    new_file_logger,
    new_logger,
    new_logger_from_env,
)
from gofr_tpu.logging.remote import RemoteLevelLogger

__all__ = [
    "Level",
    "level_from_string",
    "Logger",
    "PrettyPrint",
    "new_logger",
    "new_logger_from_env",
    "new_file_logger",
    "RemoteLevelLogger",
]
