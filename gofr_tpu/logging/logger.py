"""Leveled JSON/pretty logger.

Capability parity with the reference's ``logging/logger.go:17-196``:

* 6 levels × plain + ``*f`` formatting variants;
* JSON lines when the sink is not a TTY, colorized human format when it is;
* messages below ERROR go to stdout, ERROR+ to stderr
  (reference ``logging/logger.go:54-85``);
* structured payloads implementing :class:`PrettyPrint` render themselves in
  terminal mode (reference ``logging/logger.go:17-19,146-160``);
* ``change_level`` hot-swaps the level (used by the remote level poller,
  reference ``logging/dynamicLevelLogger.go:52-71``);
* ``new_file_logger`` for CLI apps (reference ``logging/logger.go:177-196``).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Optional, Protocol, TextIO, runtime_checkable

from gofr_tpu.logging.level import Level, level_from_string


@runtime_checkable
class PrettyPrint(Protocol):
    """Structured log payloads render themselves on terminals
    (reference ``logging/logger.go:17-19``)."""

    def pretty_print(self, fp: TextIO) -> None: ...


def _is_terminal(fp: TextIO) -> bool:
    try:
        return fp.isatty()
    except (AttributeError, ValueError):
        return False


def _jsonable(value: Any) -> Any:
    if value is None or isinstance(value, (str, int, float, bool)):
        return value
    if isinstance(value, BaseException):
        return f"{type(value).__name__}: {value}"
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if hasattr(value, "to_log_dict"):
        return _jsonable(value.to_log_dict())
    if hasattr(value, "__dict__"):
        return {k: _jsonable(v) for k, v in vars(value).items() if not k.startswith("_")}
    return str(value)


class Logger:
    """Concrete leveled logger. Thread-safe; level is hot-swappable."""

    def __init__(
        self,
        level: Level = Level.INFO,
        out: TextIO | None = None,
        err: TextIO | None = None,
        is_terminal: Optional[bool] = None,
    ) -> None:
        self.level = level
        self._out = out if out is not None else sys.stdout
        self._err = err if err is not None else sys.stderr
        self._lock = threading.Lock()
        self._is_terminal = (
            is_terminal if is_terminal is not None else _is_terminal(self._out)
        )

    # -- core ------------------------------------------------------------

    def change_level(self, level: Level) -> None:
        self.level = level

    def _logf(self, level: Level, args: tuple, fmt: Optional[str] = None) -> None:
        if level < self.level:
            return
        fp = self._err if level >= Level.ERROR else self._out
        if fmt is not None:
            # Never let a bad format string crash the caller (Go's Sprintf
            # contract: formatting errors degrade, they don't panic).
            try:
                message: Any = (fmt % args) if args else fmt
            except (TypeError, ValueError):
                message = f"{fmt} {args!r}"
        elif len(args) == 1:
            message = args[0]
        else:
            message = " ".join(str(a) for a in args)

        now = time.time()
        with self._lock:
            if self._is_terminal:
                self._pretty(fp, level, now, message)
            else:
                record = {
                    "level": level.name,
                    "time": time.strftime(
                        "%Y-%m-%dT%H:%M:%S", time.localtime(now)
                    )
                    + f".{int((now % 1) * 1e6):06d}",
                    "message": _jsonable(message),
                }
                # dumps + ONE write, not json.dump's token-at-a-time
                # streaming (~46 TextIOWrapper.write calls per record —
                # profiled as the hot-path cost of the per-request log).
                fp.write(json.dumps(record, default=str) + "\n")
            try:
                fp.flush()
            except (ValueError, OSError):
                pass

    def _pretty(self, fp: TextIO, level: Level, now: float, message: Any) -> None:
        # "LEVL [ts] message" with ANSI color, mirroring
        # reference logging/logger.go:146-160.
        ts = time.strftime("%H:%M:%S", time.localtime(now))
        fp.write(f"\x1b[38;5;{level.color}m{level.name[:4]}\x1b[0m [{ts}] ")
        if isinstance(message, PrettyPrint) and not isinstance(message, str):
            message.pretty_print(fp)
        elif isinstance(message, (dict, list)):
            fp.write(json.dumps(_jsonable(message)))
            fp.write("\n")
        else:
            fp.write(f"{message}\n")

    # -- leveled methods (reference logging/logger.go:21-38) -------------

    def debug(self, *args: Any) -> None:
        self._logf(Level.DEBUG, args)

    def debugf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.DEBUG, args, fmt)

    def log(self, *args: Any) -> None:
        self._logf(Level.INFO, args)

    def logf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.INFO, args, fmt)

    def info(self, *args: Any) -> None:
        self._logf(Level.INFO, args)

    def infof(self, fmt: str, *args: Any) -> None:
        self._logf(Level.INFO, args, fmt)

    def notice(self, *args: Any) -> None:
        self._logf(Level.NOTICE, args)

    def noticef(self, fmt: str, *args: Any) -> None:
        self._logf(Level.NOTICE, args, fmt)

    def warn(self, *args: Any) -> None:
        self._logf(Level.WARN, args)

    def warnf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.WARN, args, fmt)

    def error(self, *args: Any) -> None:
        self._logf(Level.ERROR, args)

    def errorf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.ERROR, args, fmt)

    def fatal(self, *args: Any) -> None:
        """Log at FATAL and raise SystemExit(1) (Go's ``log.Fatal`` contract)."""
        self._logf(Level.FATAL, args)
        raise SystemExit(1)

    def fatalf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.FATAL, args, fmt)
        raise SystemExit(1)


def new_logger(level: Level = Level.INFO, **kw: Any) -> Logger:
    """Reference ``logging/logger.go:163-168``."""
    return Logger(level=level, **kw)


def new_logger_from_env(config: Any = None) -> Logger:
    """Build a logger from ``LOG_LEVEL`` (reference ``container/container.go:66``)."""
    raw = config.get("LOG_LEVEL") if config is not None else os.environ.get("LOG_LEVEL")
    return Logger(level=level_from_string(raw))


def new_file_logger(path: str) -> Logger:
    """File-sink logger for CLI apps (reference ``logging/logger.go:177-196``).

    An empty path yields a silent logger, matching the reference's behavior of
    discarding output when ``CMD_LOGS_FILE`` is unset.
    """
    if not path:
        sink: TextIO = open(os.devnull, "w", encoding="utf-8")
    else:
        sink = open(path, "a", encoding="utf-8")
    return Logger(level=Level.INFO, out=sink, err=sink, is_terminal=False)
