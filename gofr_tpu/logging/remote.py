"""Remote log-level hot reload (reference ``logging/dynamicLevelLogger.go:23-106``).

A background daemon thread polls ``REMOTE_LOG_URL`` every
``REMOTE_LOG_FETCH_INTERVAL`` seconds (default 15) and hot-swaps the wrapped
logger's level. The endpoint is expected to return
``{"data": [{"serviceName": ..., "logLevel": {"LOG_LEVEL": "DEBUG"}}]}`` —
the same shape the reference parses (``dynamicLevelLogger.go:84-106``).

The fetch goes through the framework's own instrumented service client
(``service.HTTPService`` — spans, response histogram, structured service
logs), exactly as the reference builds its poller on ``service.NewHTTPService``
(``dynamicLevelLogger.go:58``): the framework's background HTTP traffic is
visible to the same observability stack as user traffic.
"""

from __future__ import annotations

import threading
from typing import Any

from gofr_tpu.logging.level import level_from_string
from gofr_tpu.logging.logger import Logger


class RemoteLevelLogger:
    """Wraps a :class:`Logger` and keeps its level in sync with a remote URL."""

    def __init__(
        self, logger: Logger, url: str, interval_s: float = 15.0,
        metrics: Any = None,
    ) -> None:
        self.logger = logger
        self._url = url
        self._interval = interval_s
        self._metrics = metrics
        self._service: Any = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None or not self._url:
            return
        self._thread = threading.Thread(
            target=self._run, name="remote-log-level", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._service is not None:
            self._service.close()
            self._service = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.fetch_and_update()

    def fetch_and_update(self) -> None:
        """One poll cycle (reference ``dynamicLevelLogger.go:73-106``)."""
        try:
            if self._service is None:
                from gofr_tpu.service.client import HTTPService

                # The level endpoint IS the address; each poll GETs "".
                # A quiet logger on the client: the poll's own debug-line
                # would otherwise echo every 15s at DEBUG level — the span
                # and histogram still record it.
                self._service = HTTPService(
                    self._url, logger=None, metrics=self._metrics, timeout=5.0
                )
            body = self._service.get("").json()
            data = body.get("data") or []
            if not data:
                return
            raw = (data[0].get("logLevel") or {}).get("LOG_LEVEL")
            if raw:
                new_level = level_from_string(raw, default=self.logger.level)
                if new_level != self.logger.level:
                    self.logger.change_level(new_level)
                    self.logger.infof("log level changed to %s remotely", new_level.name)
        except Exception as exc:  # polling must never kill the app
            self.logger.debugf("remote log level fetch failed: %s", exc)
