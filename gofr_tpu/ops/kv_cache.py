"""Slot-based KV cache for autoregressive decode (net-new; SURVEY §7 hard
part #3: persistent device state across requests).

Layout: ``[n_layers, n_slots, n_kv_heads, max_len, head_dim]`` — heads-major,
the TPU-native choice: the flash-decode kernel's per-head blocks
``[block_k, head_dim]`` tile directly onto the (8, 128) VMEM layout (a
heads-minor cache would need 1-sized blocks on the second-to-last dim,
which pallas cannot tile). The slot axis is the decode batch axis (decode
runs over ALL slots each step — static shapes, no gather/scatter), per-step
writes are position-local scatters, and the kv_heads axis shards over the
tensor-parallel mesh axis without resharding between prefill and decode.

**Int8 mode** (``quant="int8"``): K/V store as int8 with one f32 absmax
scale per (layer, slot, head, position) — decode streams the cache from
HBM at half the bytes and the cache footprint stops bounding slot count
at ``max_len × n_slots`` bf16 (VERDICT r2 next #9: an 8B model's bf16
cache is ~2 GB/slot at 8k context; int8 + scales is ~1.2 GB). Scale
layout is ``[n_layers, n_slots, n_kv_heads, 8, max_len]`` — the scale
vector a kernel needs per kv block is positions-along-lanes, and the
8-wide replicated sublane axis makes the block ``(8, block_k)``, an
exact f32 VMEM tile (a bare ``[block_k]`` vector block cannot tile).

The cache is a functional pytree; the model's prefill/decode steps return
updated buffers which XLA aliases in place when the jitted step donates them
(``gofr_tpu/serving/engine.py`` does).
"""

from __future__ import annotations

import json
import struct
import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class KVCache(NamedTuple):
    k: jnp.ndarray  # [layers, slots, kv_heads, max_len, head_dim]
    v: jnp.ndarray
    lengths: jnp.ndarray  # [slots] int32 — tokens currently in each slot
    # int8 mode only: per-position absmax scales, sublane-replicated ×8
    # ([layers, slots, kv_heads, 8, max_len] f32); None in bf16 mode.
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        quant: str = "",
    ) -> "KVCache":
        shape = (n_layers, n_slots, n_kv_heads, max_len, head_dim)
        if (quant or "").lower() == "int8":
            sshape = (n_layers, n_slots, n_kv_heads, 8, max_len)
            return cls(
                k=jnp.zeros(shape, dtype=jnp.int8),
                v=jnp.zeros(shape, dtype=jnp.int8),
                lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
                k_s=jnp.ones(sshape, dtype=jnp.float32),
                v_s=jnp.ones(sshape, dtype=jnp.float32),
            )
        if quant:
            raise ValueError(f"unsupported KV quant mode {quant!r} (int8 only)")
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def hbm_bytes(self) -> int:
        total = self.k.size * self.k.dtype.itemsize * 2
        if self.k_s is not None:
            total += self.k_s.size * self.k_s.dtype.itemsize * 2
        return int(total)


class PagedKVCache(NamedTuple):
    """Block-pool KV cache (the vLLM idea, TPU-shaped).

    The slot cache reserves ``n_slots × max_len`` HBM whether or not the
    sequences are long; the paged cache reserves a POOL of fixed-size
    blocks and maps each slot's logical positions onto pool blocks via a
    block table, so HBM scales with the tokens actually resident:

    * ``k``/``v``: ``[L, n_blocks, KV, block, hd]`` — block as the
      second-to-last axis keeps per-(block, head) tiles ``[block, hd]``,
      the same VMEM-tileable layout the slot cache uses, so the pallas
      decode kernel only changes its index_map (pool block id from the
      prefetched table instead of ``ik``);
    * ``block_table``: ``[S, max_blocks] int32`` — pool block id for
      each slot's j-th logical block (entries past the allocated count
      are 0; the allocator guarantees allocation stays ahead of the
      pipelined windows' overshoot, see engine admission);
    * ``lengths``: ``[S]`` valid logical prefix per slot;
    * ``k_s``/``v_s``: int8 mode — ``[L, n_blocks, KV, 8, block]``
      sublane-replicated scale planes, mirroring the slot cache's.

    Block 0 is a reserved PARKING block: inactive-slot writes and
    rejected-draft history land there, so it is never handed out by the
    allocator and garbage in it is never attended (table entries of
    unallocated logical blocks also point at it).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    block_table: jnp.ndarray
    lengths: jnp.ndarray
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        quant: str = "",
        block: int = 128,
        n_blocks: int = 0,
    ) -> "PagedKVCache":
        """``max_len`` is the per-slot LOGICAL cap (table width);
        ``n_blocks`` the pool size (default: slots×max_len/block — same
        capacity as the slot cache; size it smaller to oversubscribe)."""
        if max_len % block:
            raise ValueError(f"max_len {max_len} not a multiple of block {block}")
        max_blocks = max_len // block
        if n_blocks <= 0:
            n_blocks = n_slots * max_blocks + 1  # +1: parking block 0
        shape = (n_layers, n_blocks, n_kv_heads, block, head_dim)
        table = jnp.zeros((n_slots, max_blocks), dtype=jnp.int32)
        if (quant or "").lower() == "int8":
            sshape = (n_layers, n_blocks, n_kv_heads, 8, block)
            return cls(
                k=jnp.zeros(shape, dtype=jnp.int8),
                v=jnp.zeros(shape, dtype=jnp.int8),
                block_table=table,
                lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
                k_s=jnp.ones(sshape, dtype=jnp.float32),
                v_s=jnp.ones(sshape, dtype=jnp.float32),
            )
        if quant:
            raise ValueError(f"unsupported KV quant mode {quant!r} (int8 only)")
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            block_table=table,
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @property
    def n_slots(self) -> int:
        return self.block_table.shape[0]

    @property
    def block(self) -> int:
        return self.k.shape[3]

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.block_table.shape[1] * self.k.shape[3]

    def hbm_bytes(self) -> int:
        total = self.k.size * self.k.dtype.itemsize * 2
        if self.k_s is not None:
            total += self.k_s.size * self.k_s.dtype.itemsize * 2
        return int(total)

    def block_bytes(self) -> int:
        """Global bytes of ONE pool block across every layer's K/V
        (and int8-scale) planes — the unit the HBM ledger converts the
        eviction watermark's byte fractions into block counts with."""
        return self.hbm_bytes() // self.n_blocks


class BlockAllocator:
    """Host-side refcounted allocator over the paged pool's physical
    blocks (block 0 is the reserved parking block and never handed out).

    The original paged allocator was a bare free list: every block
    belonged to exactly one slot and retirement returned it. Automatic
    prefix caching (serving/radix_cache.py) shares fully-filled prompt
    blocks across requests by block-table aliasing, so ownership becomes
    counted: a block's refcount is the number of live slot tables that
    reference it plus one if the radix index holds it. A block returns
    to the free list exactly when its refcount reaches zero.

    Thread safety: admission/retirement mutate from the scheduler
    thread, but ``RadixPrefixIndex.purge_aid`` decrefs from whichever
    thread calls ``load_lora``/``unload_lora``, so the count/free-list
    transitions hold an internal lock (host bookkeeping — contention is
    nil next to a device dispatch).
    """

    def __init__(self, n_blocks: int) -> None:
        import threading

        self.n_blocks = int(n_blocks)
        self._lock = threading.Lock()
        # Pop from the end → highest ids hand out first (the original
        # free-list order; tests and the soak script watch its length).
        self._free: list[int] = list(range(1, self.n_blocks))
        self._refs: list[int] = [0] * self.n_blocks

    @property
    def free_blocks(self) -> list[int]:
        """Free-list view (length == free blocks). Treat as read-only."""
        return self._free

    @property
    def n_free(self) -> int:
        return len(self._free)

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    def alloc(self) -> Optional[int]:
        """One free block with refcount 1, or None when the pool is dry
        (callers may evict unreferenced radix-cached blocks and retry).
        """
        with self._lock:
            if not self._free:
                return None
            bid = self._free.pop()
            self._refs[bid] = 1
            return bid

    def incref(self, bid: int) -> int:
        """Add a reference (block-table aliasing / radix adoption)."""
        with self._lock:
            if self._refs[bid] <= 0:
                raise ValueError(f"incref of free block {bid}")
            self._refs[bid] += 1
            return self._refs[bid]

    def decref(self, bid: int) -> bool:
        """Drop one reference; True when this freed the block (refcount
        hit zero and it returned to the free list)."""
        with self._lock:
            if self._refs[bid] <= 0:
                raise ValueError(f"decref of free block {bid}")
            self._refs[bid] -= 1
            if self._refs[bid] == 0:
                self._free.append(bid)
                return True
            return False


@partial(jax.jit, donate_argnums=(0,))
def paged_copy_block(
    cache: "PagedKVCache", src: Any, dst: Any
) -> "PagedKVCache":
    """Copy one physical block pool→pool across every layer (K, V and
    the int8 scale planes when present) — the copy-on-write step behind
    zero-copy prefix sharing: when a cached prefix covers a slot's
    ENTIRE prompt, the finalize chunk still re-writes the last prompt
    position, so the boundary block is duplicated first and the slot's
    table points at the private copy. ``src``/``dst`` are traced int32
    scalars, so this is ONE fixed-shape compile per cache geometry; the
    donated pool aliases in place."""
    new = cache._replace(
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )
    if cache.k_s is not None:
        new = new._replace(
            k_s=cache.k_s.at[:, dst].set(cache.k_s[:, src]),
            v_s=cache.v_s.at[:, dst].set(cache.v_s[:, src]),
        )
    return new


def paged_view(
    block_table: Any,
    layer_k: Any,
    layer_v: Any,
    rows: Any,
    layer_ks: Any = None,
    layer_vs: Any = None,
) -> tuple:
    """Dense-fallback view: gather ``rows``' blocks into contiguous
    per-row caches ``[R, KV, max_len, hd]`` (+ scale planes). Materializes
    a copy — the paged flash-decode kernel indexes the pool in place
    instead; this exists for the CPU/dense path and tests.

    layer_k/layer_v: one layer's pool ``[n_blocks, KV, block, hd]``.
    """
    bt = block_table[rows]  # [R, max_blocks]
    R, MB = bt.shape
    KV, B, hd = layer_k.shape[1], layer_k.shape[2], layer_k.shape[3]
    k = layer_k[bt]  # [R, MB, KV, block, hd]
    v = layer_v[bt]
    k = k.transpose(0, 2, 1, 3, 4).reshape(R, KV, MB * B, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(R, KV, MB * B, hd)
    if layer_ks is None:
        return k, v, None, None
    ks = layer_ks[bt].transpose(0, 2, 3, 1, 4).reshape(R, KV, 8, MB * B)
    vs = layer_vs[bt].transpose(0, 2, 3, 1, 4).reshape(R, KV, 8, MB * B)
    return k, v, ks, vs


# ----------------------------------------------------------------------
# Cross-engine block shipping (disaggregated prefill/decode tiers)
# ----------------------------------------------------------------------


@dataclass(frozen=True, eq=False)  # identity eq: ndarray fields don't compare
class KVBlockPayload:
    """A batch of fully-written paged KV blocks lifted off one engine's
    pool so a sibling can alias the same content into its own — the
    transfer unit of the disaggregated prefill/decode tier
    (``service/replica_pool.py``).

    This is the HOST-BOUNCE form: the planes are numpy arrays pulled
    device→host on the exporting engine and re-uploaded block-by-block
    on the importer (one fixed-shape jitted scatter per block, so the
    importer pays no recompiles). A device-to-device path over a shared
    mesh can later replace the numpy legs without changing this seam —
    the content keys and validation travel the same either way.

    ``token_ids`` is the blocks' token content in prompt order (exactly
    ``len(blocks) × block`` ids): the importing engine inserts the
    blocks into its radix prefix index under these content keys, so the
    import IS a prefix-cache warm and admission aliases the blocks
    zero-copy — an evicted or rejected import degrades to a plain
    re-prefill, never to a wrong answer.

    ``checksum`` covers the raw plane bytes; a short or corrupt payload
    fails :meth:`verify` and the importer falls back to re-prefilling
    (the transfer failure matrix's "corrupt payload" row).
    """

    block: int
    token_ids: tuple[int, ...]
    k: np.ndarray  # [L, n, KV, block, hd] — gathered pool blocks
    v: np.ndarray
    k_s: Optional[np.ndarray] = None  # int8 mode: [L, n, KV, 8, block]
    v_s: Optional[np.ndarray] = None
    src: str = ""
    checksum: int = 0
    # Geometry fingerprint of the exporting cache; importers with a
    # different model/config/quant mode must reject, not alias garbage.
    geometry: tuple = field(default_factory=tuple)

    @property
    def n_blocks(self) -> int:
        return int(self.k.shape[1])

    def compatible_with(self, cache: "PagedKVCache") -> bool:
        """Geometry (version) match against the importing pool."""
        return (
            self.block == cache.block
            and self.geometry == cache_geometry(cache)
        )

    def nbytes(self) -> int:
        """Shipped bytes across every plane (the per-leg transfer-bytes
        counter's increment)."""
        total = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_s is not None:
            total += int(self.k_s.nbytes)
        if self.v_s is not None:
            total += int(self.v_s.nbytes)
        return total

    def verify(self) -> bool:
        """Payload integrity: the token chain covers the blocks exactly
        and the plane bytes hash to the exporter's checksum. The CRC
        verdict is memoized — a transfer retrying across decode targets
        re-verifies the SAME in-process memory, which cannot rot
        between attempts (the wire form will re-checksum on receipt
        instead)."""
        if len(self.token_ids) != self.n_blocks * self.block:
            return False
        cached = self.__dict__.get("_crc_ok")
        if cached is None:
            cached = payload_checksum(
                self.k, self.v, self.k_s, self.v_s
            ) == self.checksum
            object.__setattr__(self, "_crc_ok", cached)
        return bool(cached)


def cache_geometry(cache: "PagedKVCache") -> tuple:
    """The paged pool's compile-relevant shape signature — what must
    match exactly for a foreign block's bytes to mean the same thing
    here (layers, kv heads, block, head_dim, dtype, quant mode)."""
    L, _, KV, B, hd = cache.k.shape
    return (L, KV, B, hd, str(cache.k.dtype), cache.k_s is not None)


def payload_checksum(
    k: np.ndarray,
    v: np.ndarray,
    k_s: Optional[np.ndarray] = None,
    v_s: Optional[np.ndarray] = None,
) -> int:
    crc = zlib.crc32(np.ascontiguousarray(k).tobytes())
    crc = zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)
    if k_s is not None:
        crc = zlib.crc32(np.ascontiguousarray(k_s).tobytes(), crc)
    if v_s is not None:
        crc = zlib.crc32(np.ascontiguousarray(v_s).tobytes(), crc)
    return crc


def export_blocks(
    cache: "PagedKVCache",
    block_ids: list[int],
    token_ids: list[int],
    src: str = "",
) -> KVBlockPayload:
    """Pull ``block_ids``' fully-written pool blocks to host as a
    shippable payload (one gather + one device→host copy per plane —
    the deliberate host bounce of the tier-transfer path, not a hot-
    path sync; the caller is the exporting scheduler at prefill
    finalize, where the blocks are immutable)."""
    idx = np.asarray(block_ids, dtype=np.int32)
    k = np.asarray(jax.device_get(cache.k[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    v = np.asarray(jax.device_get(cache.v[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    k_s = v_s = None
    if cache.k_s is not None:
        k_s = np.asarray(jax.device_get(cache.k_s[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
        v_s = np.asarray(jax.device_get(cache.v_s[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    return KVBlockPayload(
        block=cache.block,
        token_ids=tuple(int(t) for t in token_ids),
        k=k, v=v, k_s=k_s, v_s=v_s, src=src,
        checksum=payload_checksum(k, v, k_s, v_s),
        geometry=cache_geometry(cache),
    )


@partial(jax.jit, donate_argnums=(0,))
def paged_insert_block(
    cache: "PagedKVCache",
    dst: Any,
    k_blk: Any,
    v_blk: Any,
    k_s_blk: Any = None,
    v_s_blk: Any = None,
) -> "PagedKVCache":
    """Write one imported block's planes into pool block ``dst`` (the
    import half of the transfer seam). ``dst`` is a traced int32
    scalar and the block operands are fixed ``[L, KV, block, hd]``
    shapes, so this is ONE compile per cache geometry no matter how
    many blocks an import carries; the donated pool aliases in place
    (same discipline as :func:`paged_copy_block`)."""
    new = cache._replace(
        k=cache.k.at[:, dst].set(k_blk),
        v=cache.v.at[:, dst].set(v_blk),
    )
    if cache.k_s is not None and k_s_blk is not None:
        new = new._replace(
            k_s=cache.k_s.at[:, dst].set(k_s_blk),
            v_s=cache.v_s.at[:, dst].set(v_s_blk),
        )
    return new


# ----------------------------------------------------------------------
# Device leg: pool→pool block shipping without the host bounce
# ----------------------------------------------------------------------


@dataclass(frozen=True, eq=False)  # identity eq: device-array fields
class DeviceKVPayload:
    """The device-leg twin of :class:`KVBlockPayload`: per-block cache
    planes extracted as DEVICE arrays (one fixed-shape jitted gather per
    block, :func:`paged_extract_block`) and written into the importing
    pool with :func:`paged_move_block` — the bytes move over ICI/DMA
    (or stay in place when both pools share a device), never through
    host memory. Only usable between engines in one process on a shared
    JAX runtime; the pool's transfer ladder falls back to the wire or
    host-bounce form for everything else.

    Content keys (``token_ids``), the geometry fingerprint, and all
    radix bookkeeping stay host-side and travel exactly like the
    host-bounce payload's. There is deliberately no byte checksum: the
    planes never leave device memory, where in-process bytes cannot rot
    between export and import, and computing a CRC would itself be the
    host pull this leg exists to remove.
    """

    block: int
    token_ids: tuple[int, ...]
    #: per-block device planes, each ``[L, KV, block, hd]`` on the
    #: EXPORTING engine's sharding (the importer re-places them).
    k_blocks: tuple[Any, ...]
    v_blocks: tuple[Any, ...]
    #: int8 mode: per-block scale planes ``[L, KV, 8, block]``.
    k_s_blocks: Optional[tuple[Any, ...]] = None
    v_s_blocks: Optional[tuple[Any, ...]] = None
    src: str = ""
    geometry: tuple = field(default_factory=tuple)

    @property
    def n_blocks(self) -> int:
        return len(self.k_blocks)

    def compatible_with(self, cache: "PagedKVCache") -> bool:
        """Geometry (version) match against the importing pool."""
        return (
            self.block == cache.block
            and self.geometry == cache_geometry(cache)
        )

    def verify(self) -> bool:
        """Structural integrity: the token chain covers the blocks
        exactly and the scale planes match the quant mode. No CRC leg —
        see the class docstring."""
        if len(self.token_ids) != self.n_blocks * self.block:
            return False
        if len(self.v_blocks) != self.n_blocks:
            return False
        quant = self.geometry[-1] if self.geometry else False
        if bool(quant) != (self.k_s_blocks is not None):
            return False
        return True

    def nbytes(self) -> int:
        """Shipped bytes, computed from shapes host-side (never pulls
        a plane)."""
        total = 0
        for group in (
            self.k_blocks, self.v_blocks,
            self.k_s_blocks or (), self.v_s_blocks or (),
        ):
            for blk in group:
                total += int(np.prod(blk.shape)) * blk.dtype.itemsize
        return total


@jax.jit
def paged_extract_block(
    cache: "PagedKVCache", src: Any
) -> tuple[Any, Any, Any, Any]:
    """Lift one physical block's planes out of the pool as fresh DEVICE
    arrays ``([L, KV, block, hd]×2, [L, KV, 8, block]×2 | None)`` — the
    export half of the device leg. ``src`` is a traced int32 scalar, so
    this is ONE fixed-shape compile per cache geometry no matter how
    many blocks a transfer carries; on a GSPMD-sharded pool the result
    keeps the pool's head-axis sharding, so nothing gathers."""
    k_blk = cache.k[:, src]
    v_blk = cache.v[:, src]
    if cache.k_s is not None:
        return k_blk, v_blk, cache.k_s[:, src], cache.v_s[:, src]
    return k_blk, v_blk, None, None


@partial(jax.jit, donate_argnums=(0,))
def paged_move_block(
    cache: "PagedKVCache",
    dst: Any,
    k_blk: Any,
    v_blk: Any,
    k_s_blk: Any = None,
    v_s_blk: Any = None,
) -> "PagedKVCache":
    """Write one DEVICE-resident block's planes into pool block ``dst``
    — the import half of the device leg. Identical donation/fixed-shape
    discipline to :func:`paged_insert_block`; the difference is the
    contract on the operands: they are already on the importing
    engine's devices (placed shard-to-shard with an explicit
    ``device_put`` when the pools' meshes differ), so the write never
    touches host memory. graftlint GL018 pins that contract: no
    ``device_get``/``np.asarray`` of cache planes may appear in
    ``paged_move*``/``*_device_leg`` code."""
    new = cache._replace(
        k=cache.k.at[:, dst].set(k_blk),
        v=cache.v.at[:, dst].set(v_blk),
    )
    if cache.k_s is not None and k_s_blk is not None:
        new = new._replace(
            k_s=cache.k_s.at[:, dst].set(k_s_blk),
            v_s=cache.v_s.at[:, dst].set(v_s_blk),
        )
    return new


# ----------------------------------------------------------------------
# Wire leg: length-prefixed binary codec for remote decode replicas
# ----------------------------------------------------------------------

#: Wire format magic/version. Bump on any framing change — the importer
#: rejects unknown magics instead of guessing.
WIRE_MAGIC = b"KVB1"


def _np_dtype(name: str) -> np.dtype:
    """``str(dtype)`` → dtype, including the ml_dtypes extras (bf16)
    numpy itself cannot name. Raises ``ValueError`` on anything else —
    the wire decoder's one rejection currency."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        dtype = getattr(ml_dtypes, name, None)
        if dtype is None:
            raise ValueError(f"unknown plane dtype {name!r}") from None
        return np.dtype(dtype)


def payload_to_wire(payload: KVBlockPayload) -> bytes:
    """Serialize a host-bounce payload for the wire leg: ``KVB1`` magic,
    a u32-length-prefixed JSON header (geometry fingerprint, content
    keys, crc32, plane shapes/dtypes), then each plane's raw bytes
    u64-length-prefixed in header order. The receiver re-checksums the
    planes on receipt (:func:`payload_from_wire` builds a fresh
    :class:`KVBlockPayload`, whose ``verify()`` recomputes the CRC), so
    a corrupt body degrades to fused serving, never a wrong answer."""
    planes: list[np.ndarray] = [payload.k, payload.v]
    names = ["k", "v"]
    if payload.k_s is not None and payload.v_s is not None:
        planes += [payload.k_s, payload.v_s]
        names += ["k_s", "v_s"]
    header = {
        "block": payload.block,
        "token_ids": list(payload.token_ids),
        "src": payload.src,
        "checksum": payload.checksum,
        "geometry": list(payload.geometry),
        "planes": [
            {
                "name": name,
                "shape": list(plane.shape),
                "dtype": str(plane.dtype),
            }
            for name, plane in zip(names, planes)
        ],
    }
    head = json.dumps(header).encode()
    parts = [WIRE_MAGIC, struct.pack(">I", len(head)), head]
    for plane in planes:
        raw = np.ascontiguousarray(plane).tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def payload_from_wire(data: bytes) -> KVBlockPayload:
    """Parse a wire-leg body back into a :class:`KVBlockPayload`.
    Raises ``ValueError`` on any framing violation (bad magic, short
    body, shape/byte-count mismatch) — the import endpoint maps that to
    a 400 ``rejected`` reply and the exporter degrades to the next
    rung. Byte-level corruption INSIDE a plane is caught later by
    ``verify()``'s CRC recomputation against the header checksum."""
    if len(data) < 8 or data[:4] != WIRE_MAGIC:
        raise ValueError("tier-import body lacks the KVB1 magic")
    (head_len,) = struct.unpack(">I", data[4:8])
    if len(data) < 8 + head_len:
        raise ValueError("tier-import header truncated")
    try:
        header = json.loads(data[8:8 + head_len].decode())
    except Exception as exc:
        raise ValueError(f"tier-import header unparseable: {exc}") from exc
    offset = 8 + head_len
    planes: dict[str, np.ndarray] = {}
    # Every malformed-header shape (missing keys, wrong types, bogus
    # dtypes) is the same rejection: the decoder's ONE exception
    # currency is ValueError, which the import endpoint maps to a 400
    # "rejected" — never a 5xx, whatever bytes arrive.
    try:
        for meta in header.get("planes", []):
            if len(data) < offset + 8:
                raise ValueError("tier-import plane length truncated")
            (nbytes,) = struct.unpack(">Q", data[offset:offset + 8])
            offset += 8
            if len(data) < offset + nbytes:
                raise ValueError(
                    f"tier-import plane {meta.get('name')!r} truncated"
                )
            dtype = _np_dtype(str(meta["dtype"]))
            shape = tuple(int(s) for s in meta["shape"])
            if int(np.prod(shape)) * dtype.itemsize != nbytes:
                raise ValueError(
                    f"tier-import plane {meta.get('name')!r} byte count "
                    f"does not match its declared shape"
                )
            planes[str(meta["name"])] = np.frombuffer(
                data, dtype=dtype, count=int(np.prod(shape)), offset=offset
            ).reshape(shape)
            offset += nbytes
        if "k" not in planes or "v" not in planes:
            raise ValueError("tier-import body is missing K/V planes")
        return KVBlockPayload(
            block=int(header["block"]),
            token_ids=tuple(int(t) for t in header.get("token_ids", ())),
            k=planes["k"],
            v=planes["v"],
            k_s=planes.get("k_s"),
            v_s=planes.get("v_s"),
            src=str(header.get("src", "")),
            checksum=int(header.get("checksum", 0)),
            geometry=tuple(header.get("geometry", ())),
        )
    except (KeyError, TypeError, AttributeError, OverflowError,
            struct.error) as exc:
        raise ValueError(
            f"tier-import header malformed: {exc!r}"
        ) from exc


# ----------------------------------------------------------------------
# DMA leg: handle-bearing wire variant (cross-process transfer server)
# ----------------------------------------------------------------------

#: Handle wire format magic/version. A ``KVH1`` body carries NO plane
#: bytes — only a claim ticket against the exporter's transfer server
#: (``service/dma.py``); the importer redeems it with a bounded fetch
#: and only then sees a full ``KVB1`` payload. Sharing the first-4-byte
#: dispatch with :data:`WIRE_MAGIC` lets ``POST /ops/tier-import``
#: accept either form on the same endpoint.
HANDLE_MAGIC = b"KVH1"


@dataclass(frozen=True)
class KVHandlePayload:
    """A CLAIM TICKET for KV blocks staged on the exporting process's
    transfer server — the ``dma`` leg's transfer unit. Where
    :class:`KVBlockPayload` ships the plane bytes inline, this ships
    only an (address, key) pair plus the content metadata the importer
    needs for admission decisions *before* paying for the fetch:
    geometry fingerprint, token chain, byte count, and the exporter's
    checksum (re-verified against the fetched bytes, so a transfer
    server handing back the wrong staging entry is caught as a stale
    handle, never aliased as garbage).

    The fields deliberately mirror the host-bounce payload's metadata
    so validation code (``compatible_with``/``n_blocks``) reads the
    same; only ``verify()`` differs — structurally true here, because
    integrity is proven after the fetch, on the real bytes."""

    address: str  # "host:port" of the exporter's DmaTransferServer
    key: str      # opaque staging key (single-use, TTL-bounded)
    block: int
    token_ids: tuple[int, ...]
    src: str = ""
    checksum: int = 0
    geometry: tuple = field(default_factory=tuple)
    nbytes_hint: int = 0  # staged wire-body size (flow-control budget)

    @property
    def n_blocks(self) -> int:
        return len(self.token_ids) // self.block if self.block else 0

    def compatible_with(self, cache: "PagedKVCache") -> bool:
        """Same geometry gate as the inline payload — a handle whose
        fingerprint can't match is rejected before any socket opens."""
        return (
            self.block == cache.block
            and self.geometry == cache_geometry(cache)
        )

    def nbytes(self) -> int:
        return int(self.nbytes_hint)

    def verify(self) -> bool:
        """Structural check only: the token chain must tile the blocks.
        Byte integrity is decided by the post-fetch CRC against
        ``checksum`` (``service/dma.py`` raises ``stale`` on mismatch)."""
        return (
            self.block > 0
            and len(self.token_ids) % self.block == 0
            and len(self.token_ids) > 0
        )


def handle_to_wire(handle: KVHandlePayload) -> bytes:
    """Serialize a transfer-server claim ticket: ``KVH1`` magic + a
    u32-length-prefixed JSON header, no plane bytes. Tiny by design —
    the dma leg's HTTP POST carries O(100) bytes however many blocks
    the staged payload holds."""
    header = {
        "address": handle.address,
        "key": handle.key,
        "block": handle.block,
        "token_ids": list(handle.token_ids),
        "src": handle.src,
        "checksum": handle.checksum,
        "geometry": list(handle.geometry),
        "nbytes": handle.nbytes_hint,
    }
    head = json.dumps(header).encode()
    return b"".join([HANDLE_MAGIC, struct.pack(">I", len(head)), head])


def handle_from_wire(data: bytes) -> KVHandlePayload:
    """Parse a ``KVH1`` body back into a :class:`KVHandlePayload`.
    Exactly :func:`payload_from_wire`'s contract: every malformed shape
    raises ``ValueError`` — the import endpoint's one rejection
    currency, mapped to a 400 ``rejected`` reply."""
    if len(data) < 8 or data[:4] != HANDLE_MAGIC:
        raise ValueError("tier-import body lacks the KVH1 magic")
    (head_len,) = struct.unpack(">I", data[4:8])
    if len(data) < 8 + head_len:
        raise ValueError("tier-import handle header truncated")
    try:
        header = json.loads(data[8:8 + head_len].decode())
        address = str(header["address"])
        if ":" not in address:
            raise ValueError(f"handle address {address!r} lacks a port")
        return KVHandlePayload(
            address=address,
            key=str(header["key"]),
            block=int(header["block"]),
            token_ids=tuple(int(t) for t in header.get("token_ids", ())),
            src=str(header.get("src", "")),
            checksum=int(header.get("checksum", 0)),
            geometry=tuple(header.get("geometry", ())),
            nbytes_hint=int(header.get("nbytes", 0)),
        )
    except ValueError:
        raise
    except (KeyError, TypeError, AttributeError, OverflowError,
            struct.error, UnicodeDecodeError) as exc:
        raise ValueError(
            f"tier-import handle header malformed: {exc!r}"
        ) from exc


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Absmax-int8 quantize K/V rows over the trailing head_dim axis.

    x: [..., head_dim] → (q int8 same shape, scale f32 [...]) — one scalar
    scale per (token, head) row, the standard KV-cache granularity.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def fake_quantize_kv(x: jnp.ndarray) -> jnp.ndarray:
    """Quantize → dequantize (same dtype out). The split decode/verify
    paths attend a token's K/V BEFORE it is committed to an int8 cache;
    running the fresh values through the quantizer first makes what is
    attended bit-identical to what later steps will read back — and
    re-quantizing the result at commit time reproduces the same int8
    (the max element maps to exactly ±127, so the absmax scale is a
    fixed point)."""
    q, scale = quantize_kv(x)
    return (q.astype(jnp.float32) * scale[..., None]).astype(x.dtype)
