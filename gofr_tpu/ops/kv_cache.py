"""Slot-based KV cache for autoregressive decode (net-new; SURVEY §7 hard
part #3: persistent device state across requests).

Layout: ``[n_layers, n_slots, n_kv_heads, max_len, head_dim]`` — heads-major,
the TPU-native choice: the flash-decode kernel's per-head blocks
``[block_k, head_dim]`` tile directly onto the (8, 128) VMEM layout (a
heads-minor cache would need 1-sized blocks on the second-to-last dim,
which pallas cannot tile). The slot axis is the decode batch axis (decode
runs over ALL slots each step — static shapes, no gather/scatter), per-step
writes are position-local scatters, and the kv_heads axis shards over the
tensor-parallel mesh axis without resharding between prefill and decode.

**Int8 mode** (``quant="int8"``): K/V store as int8 with one f32 absmax
scale per (layer, slot, head, position) — decode streams the cache from
HBM at half the bytes and the cache footprint stops bounding slot count
at ``max_len × n_slots`` bf16 (VERDICT r2 next #9: an 8B model's bf16
cache is ~2 GB/slot at 8k context; int8 + scales is ~1.2 GB). Scale
layout is ``[n_layers, n_slots, n_kv_heads, 8, max_len]`` — the scale
vector a kernel needs per kv block is positions-along-lanes, and the
8-wide replicated sublane axis makes the block ``(8, block_k)``, an
exact f32 VMEM tile (a bare ``[block_k]`` vector block cannot tile).

The cache is a functional pytree; the model's prefill/decode steps return
updated buffers which XLA aliases in place when the jitted step donates them
(``gofr_tpu/serving/engine.py`` does).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class KVCache(NamedTuple):
    k: jnp.ndarray  # [layers, slots, kv_heads, max_len, head_dim]
    v: jnp.ndarray
    lengths: jnp.ndarray  # [slots] int32 — tokens currently in each slot
    # int8 mode only: per-position absmax scales, sublane-replicated ×8
    # ([layers, slots, kv_heads, 8, max_len] f32); None in bf16 mode.
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quant: str = "",
    ) -> "KVCache":
        shape = (n_layers, n_slots, n_kv_heads, max_len, head_dim)
        if (quant or "").lower() == "int8":
            sshape = (n_layers, n_slots, n_kv_heads, 8, max_len)
            return cls(
                k=jnp.zeros(shape, dtype=jnp.int8),
                v=jnp.zeros(shape, dtype=jnp.int8),
                lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
                k_s=jnp.ones(sshape, dtype=jnp.float32),
                v_s=jnp.ones(sshape, dtype=jnp.float32),
            )
        if quant:
            raise ValueError(f"unsupported KV quant mode {quant!r} (int8 only)")
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def hbm_bytes(self) -> int:
        total = self.k.size * self.k.dtype.itemsize * 2
        if self.k_s is not None:
            total += self.k_s.size * self.k_s.dtype.itemsize * 2
        return int(total)


class PagedKVCache(NamedTuple):
    """Block-pool KV cache (the vLLM idea, TPU-shaped).

    The slot cache reserves ``n_slots × max_len`` HBM whether or not the
    sequences are long; the paged cache reserves a POOL of fixed-size
    blocks and maps each slot's logical positions onto pool blocks via a
    block table, so HBM scales with the tokens actually resident:

    * ``k``/``v``: ``[L, n_blocks, KV, block, hd]`` — block as the
      second-to-last axis keeps per-(block, head) tiles ``[block, hd]``,
      the same VMEM-tileable layout the slot cache uses, so the pallas
      decode kernel only changes its index_map (pool block id from the
      prefetched table instead of ``ik``);
    * ``block_table``: ``[S, max_blocks] int32`` — pool block id for
      each slot's j-th logical block (entries past the allocated count
      are 0; the allocator guarantees allocation stays ahead of the
      pipelined windows' overshoot, see engine admission);
    * ``lengths``: ``[S]`` valid logical prefix per slot;
    * ``k_s``/``v_s``: int8 mode — ``[L, n_blocks, KV, 8, block]``
      sublane-replicated scale planes, mirroring the slot cache's.

    Block 0 is a reserved PARKING block: inactive-slot writes and
    rejected-draft history land there, so it is never handed out by the
    allocator and garbage in it is never attended (table entries of
    unallocated logical blocks also point at it).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    block_table: jnp.ndarray
    lengths: jnp.ndarray
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quant: str = "",
        block: int = 128,
        n_blocks: int = 0,
    ) -> "PagedKVCache":
        """``max_len`` is the per-slot LOGICAL cap (table width);
        ``n_blocks`` the pool size (default: slots×max_len/block — same
        capacity as the slot cache; size it smaller to oversubscribe)."""
        if max_len % block:
            raise ValueError(f"max_len {max_len} not a multiple of block {block}")
        max_blocks = max_len // block
        if n_blocks <= 0:
            n_blocks = n_slots * max_blocks + 1  # +1: parking block 0
        shape = (n_layers, n_blocks, n_kv_heads, block, head_dim)
        table = jnp.zeros((n_slots, max_blocks), dtype=jnp.int32)
        if (quant or "").lower() == "int8":
            sshape = (n_layers, n_blocks, n_kv_heads, 8, block)
            return cls(
                k=jnp.zeros(shape, dtype=jnp.int8),
                v=jnp.zeros(shape, dtype=jnp.int8),
                block_table=table,
                lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
                k_s=jnp.ones(sshape, dtype=jnp.float32),
                v_s=jnp.ones(sshape, dtype=jnp.float32),
            )
        if quant:
            raise ValueError(f"unsupported KV quant mode {quant!r} (int8 only)")
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            block_table=table,
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @property
    def n_slots(self) -> int:
        return self.block_table.shape[0]

    @property
    def block(self) -> int:
        return self.k.shape[3]

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.block_table.shape[1] * self.k.shape[3]

    def hbm_bytes(self) -> int:
        total = self.k.size * self.k.dtype.itemsize * 2
        if self.k_s is not None:
            total += self.k_s.size * self.k_s.dtype.itemsize * 2
        return int(total)

    def block_bytes(self) -> int:
        """Global bytes of ONE pool block across every layer's K/V
        (and int8-scale) planes — the unit the HBM ledger converts the
        eviction watermark's byte fractions into block counts with."""
        return self.hbm_bytes() // self.n_blocks


class BlockAllocator:
    """Host-side refcounted allocator over the paged pool's physical
    blocks (block 0 is the reserved parking block and never handed out).

    The original paged allocator was a bare free list: every block
    belonged to exactly one slot and retirement returned it. Automatic
    prefix caching (serving/radix_cache.py) shares fully-filled prompt
    blocks across requests by block-table aliasing, so ownership becomes
    counted: a block's refcount is the number of live slot tables that
    reference it plus one if the radix index holds it. A block returns
    to the free list exactly when its refcount reaches zero.

    Thread safety: admission/retirement mutate from the scheduler
    thread, but ``RadixPrefixIndex.purge_aid`` decrefs from whichever
    thread calls ``load_lora``/``unload_lora``, so the count/free-list
    transitions hold an internal lock (host bookkeeping — contention is
    nil next to a device dispatch).
    """

    def __init__(self, n_blocks: int) -> None:
        import threading

        self.n_blocks = int(n_blocks)
        self._lock = threading.Lock()
        # Pop from the end → highest ids hand out first (the original
        # free-list order; tests and the soak script watch its length).
        self._free: list[int] = list(range(1, self.n_blocks))
        self._refs: list[int] = [0] * self.n_blocks

    @property
    def free_blocks(self) -> list[int]:
        """Free-list view (length == free blocks). Treat as read-only."""
        return self._free

    @property
    def n_free(self) -> int:
        return len(self._free)

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    def alloc(self) -> Optional[int]:
        """One free block with refcount 1, or None when the pool is dry
        (callers may evict unreferenced radix-cached blocks and retry).
        """
        with self._lock:
            if not self._free:
                return None
            bid = self._free.pop()
            self._refs[bid] = 1
            return bid

    def incref(self, bid: int) -> int:
        """Add a reference (block-table aliasing / radix adoption)."""
        with self._lock:
            if self._refs[bid] <= 0:
                raise ValueError(f"incref of free block {bid}")
            self._refs[bid] += 1
            return self._refs[bid]

    def decref(self, bid: int) -> bool:
        """Drop one reference; True when this freed the block (refcount
        hit zero and it returned to the free list)."""
        with self._lock:
            if self._refs[bid] <= 0:
                raise ValueError(f"decref of free block {bid}")
            self._refs[bid] -= 1
            if self._refs[bid] == 0:
                self._free.append(bid)
                return True
            return False


@partial(jax.jit, donate_argnums=(0,))
def paged_copy_block(cache: "PagedKVCache", src, dst) -> "PagedKVCache":
    """Copy one physical block pool→pool across every layer (K, V and
    the int8 scale planes when present) — the copy-on-write step behind
    zero-copy prefix sharing: when a cached prefix covers a slot's
    ENTIRE prompt, the finalize chunk still re-writes the last prompt
    position, so the boundary block is duplicated first and the slot's
    table points at the private copy. ``src``/``dst`` are traced int32
    scalars, so this is ONE fixed-shape compile per cache geometry; the
    donated pool aliases in place."""
    new = cache._replace(
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )
    if cache.k_s is not None:
        new = new._replace(
            k_s=cache.k_s.at[:, dst].set(cache.k_s[:, src]),
            v_s=cache.v_s.at[:, dst].set(cache.v_s[:, src]),
        )
    return new


def paged_view(block_table, layer_k, layer_v, rows, layer_ks=None,
               layer_vs=None):
    """Dense-fallback view: gather ``rows``' blocks into contiguous
    per-row caches ``[R, KV, max_len, hd]`` (+ scale planes). Materializes
    a copy — the paged flash-decode kernel indexes the pool in place
    instead; this exists for the CPU/dense path and tests.

    layer_k/layer_v: one layer's pool ``[n_blocks, KV, block, hd]``.
    """
    bt = block_table[rows]  # [R, max_blocks]
    R, MB = bt.shape
    KV, B, hd = layer_k.shape[1], layer_k.shape[2], layer_k.shape[3]
    k = layer_k[bt]  # [R, MB, KV, block, hd]
    v = layer_v[bt]
    k = k.transpose(0, 2, 1, 3, 4).reshape(R, KV, MB * B, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(R, KV, MB * B, hd)
    if layer_ks is None:
        return k, v, None, None
    ks = layer_ks[bt].transpose(0, 2, 3, 1, 4).reshape(R, KV, 8, MB * B)
    vs = layer_vs[bt].transpose(0, 2, 3, 1, 4).reshape(R, KV, 8, MB * B)
    return k, v, ks, vs


# ----------------------------------------------------------------------
# Cross-engine block shipping (disaggregated prefill/decode tiers)
# ----------------------------------------------------------------------


@dataclass(frozen=True, eq=False)  # identity eq: ndarray fields don't compare
class KVBlockPayload:
    """A batch of fully-written paged KV blocks lifted off one engine's
    pool so a sibling can alias the same content into its own — the
    transfer unit of the disaggregated prefill/decode tier
    (``service/replica_pool.py``).

    This is the HOST-BOUNCE form: the planes are numpy arrays pulled
    device→host on the exporting engine and re-uploaded block-by-block
    on the importer (one fixed-shape jitted scatter per block, so the
    importer pays no recompiles). A device-to-device path over a shared
    mesh can later replace the numpy legs without changing this seam —
    the content keys and validation travel the same either way.

    ``token_ids`` is the blocks' token content in prompt order (exactly
    ``len(blocks) × block`` ids): the importing engine inserts the
    blocks into its radix prefix index under these content keys, so the
    import IS a prefix-cache warm and admission aliases the blocks
    zero-copy — an evicted or rejected import degrades to a plain
    re-prefill, never to a wrong answer.

    ``checksum`` covers the raw plane bytes; a short or corrupt payload
    fails :meth:`verify` and the importer falls back to re-prefilling
    (the transfer failure matrix's "corrupt payload" row).
    """

    block: int
    token_ids: tuple[int, ...]
    k: np.ndarray  # [L, n, KV, block, hd] — gathered pool blocks
    v: np.ndarray
    k_s: Optional[np.ndarray] = None  # int8 mode: [L, n, KV, 8, block]
    v_s: Optional[np.ndarray] = None
    src: str = ""
    checksum: int = 0
    # Geometry fingerprint of the exporting cache; importers with a
    # different model/config/quant mode must reject, not alias garbage.
    geometry: tuple = field(default_factory=tuple)

    @property
    def n_blocks(self) -> int:
        return int(self.k.shape[1])

    def compatible_with(self, cache: "PagedKVCache") -> bool:
        """Geometry (version) match against the importing pool."""
        return (
            self.block == cache.block
            and self.geometry == cache_geometry(cache)
        )

    def verify(self) -> bool:
        """Payload integrity: the token chain covers the blocks exactly
        and the plane bytes hash to the exporter's checksum. The CRC
        verdict is memoized — a transfer retrying across decode targets
        re-verifies the SAME in-process memory, which cannot rot
        between attempts (the wire form will re-checksum on receipt
        instead)."""
        if len(self.token_ids) != self.n_blocks * self.block:
            return False
        cached = self.__dict__.get("_crc_ok")
        if cached is None:
            cached = payload_checksum(
                self.k, self.v, self.k_s, self.v_s
            ) == self.checksum
            object.__setattr__(self, "_crc_ok", cached)
        return bool(cached)


def cache_geometry(cache: "PagedKVCache") -> tuple:
    """The paged pool's compile-relevant shape signature — what must
    match exactly for a foreign block's bytes to mean the same thing
    here (layers, kv heads, block, head_dim, dtype, quant mode)."""
    L, _, KV, B, hd = cache.k.shape
    return (L, KV, B, hd, str(cache.k.dtype), cache.k_s is not None)


def payload_checksum(
    k: np.ndarray,
    v: np.ndarray,
    k_s: Optional[np.ndarray] = None,
    v_s: Optional[np.ndarray] = None,
) -> int:
    crc = zlib.crc32(np.ascontiguousarray(k).tobytes())
    crc = zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)
    if k_s is not None:
        crc = zlib.crc32(np.ascontiguousarray(k_s).tobytes(), crc)
    if v_s is not None:
        crc = zlib.crc32(np.ascontiguousarray(v_s).tobytes(), crc)
    return crc


def export_blocks(
    cache: "PagedKVCache",
    block_ids: list[int],
    token_ids: list[int],
    src: str = "",
) -> KVBlockPayload:
    """Pull ``block_ids``' fully-written pool blocks to host as a
    shippable payload (one gather + one device→host copy per plane —
    the deliberate host bounce of the tier-transfer path, not a hot-
    path sync; the caller is the exporting scheduler at prefill
    finalize, where the blocks are immutable)."""
    idx = np.asarray(block_ids, dtype=np.int32)
    k = np.asarray(jax.device_get(cache.k[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    v = np.asarray(jax.device_get(cache.v[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    k_s = v_s = None
    if cache.k_s is not None:
        k_s = np.asarray(jax.device_get(cache.k_s[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
        v_s = np.asarray(jax.device_get(cache.v_s[:, idx]))  # graftlint: disable=GL001 — the host bounce IS the transfer
    return KVBlockPayload(
        block=cache.block,
        token_ids=tuple(int(t) for t in token_ids),
        k=k, v=v, k_s=k_s, v_s=v_s, src=src,
        checksum=payload_checksum(k, v, k_s, v_s),
        geometry=cache_geometry(cache),
    )


@partial(jax.jit, donate_argnums=(0,))
def paged_insert_block(
    cache: "PagedKVCache",
    dst: Any,
    k_blk: Any,
    v_blk: Any,
    k_s_blk: Any = None,
    v_s_blk: Any = None,
) -> "PagedKVCache":
    """Write one imported block's planes into pool block ``dst`` (the
    import half of the transfer seam). ``dst`` is a traced int32
    scalar and the block operands are fixed ``[L, KV, block, hd]``
    shapes, so this is ONE compile per cache geometry no matter how
    many blocks an import carries; the donated pool aliases in place
    (same discipline as :func:`paged_copy_block`)."""
    new = cache._replace(
        k=cache.k.at[:, dst].set(k_blk),
        v=cache.v.at[:, dst].set(v_blk),
    )
    if cache.k_s is not None and k_s_blk is not None:
        new = new._replace(
            k_s=cache.k_s.at[:, dst].set(k_s_blk),
            v_s=cache.v_s.at[:, dst].set(v_s_blk),
        )
    return new


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Absmax-int8 quantize K/V rows over the trailing head_dim axis.

    x: [..., head_dim] → (q int8 same shape, scale f32 [...]) — one scalar
    scale per (token, head) row, the standard KV-cache granularity.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def fake_quantize_kv(x: jnp.ndarray) -> jnp.ndarray:
    """Quantize → dequantize (same dtype out). The split decode/verify
    paths attend a token's K/V BEFORE it is committed to an int8 cache;
    running the fresh values through the quantizer first makes what is
    attended bit-identical to what later steps will read back — and
    re-quantizing the result at commit time reproduces the same int8
    (the max element maps to exactly ±127, so the absmax scale is a
    fixed point)."""
    q, scale = quantize_kv(x)
    return (q.astype(jnp.float32) * scale[..., None]).astype(x.dtype)
