"""Slot-based KV cache for autoregressive decode (net-new; SURVEY §7 hard
part #3: persistent device state across requests).

Layout: ``[n_layers, n_slots, n_kv_heads, max_len, head_dim]`` — heads-major,
the TPU-native choice: the flash-decode kernel's per-head blocks
``[block_k, head_dim]`` tile directly onto the (8, 128) VMEM layout (a
heads-minor cache would need 1-sized blocks on the second-to-last dim,
which pallas cannot tile). The slot axis is the decode batch axis (decode
runs over ALL slots each step — static shapes, no gather/scatter), per-step
writes are position-local scatters, and the kv_heads axis shards over the
tensor-parallel mesh axis without resharding between prefill and decode.

The cache is a functional pytree; the model's prefill/decode steps return
updated buffers which XLA aliases in place when the jitted step donates them
(``gofr_tpu/serving/engine.py`` does).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jnp.ndarray  # [layers, slots, kv_heads, max_len, head_dim]
    v: jnp.ndarray
    lengths: jnp.ndarray  # [slots] int32 — tokens currently in each slot

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "KVCache":
        shape = (n_layers, n_slots, n_kv_heads, max_len, head_dim)
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def hbm_bytes(self) -> int:
        return int(self.k.size * self.k.dtype.itemsize * 2)
