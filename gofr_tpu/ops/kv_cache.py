"""Slot-based KV cache for autoregressive decode (net-new; SURVEY §7 hard
part #3: persistent device state across requests).

Layout: ``[n_layers, n_slots, n_kv_heads, max_len, head_dim]`` — heads-major,
the TPU-native choice: the flash-decode kernel's per-head blocks
``[block_k, head_dim]`` tile directly onto the (8, 128) VMEM layout (a
heads-minor cache would need 1-sized blocks on the second-to-last dim,
which pallas cannot tile). The slot axis is the decode batch axis (decode
runs over ALL slots each step — static shapes, no gather/scatter), per-step
writes are position-local scatters, and the kv_heads axis shards over the
tensor-parallel mesh axis without resharding between prefill and decode.

**Int8 mode** (``quant="int8"``): K/V store as int8 with one f32 absmax
scale per (layer, slot, head, position) — decode streams the cache from
HBM at half the bytes and the cache footprint stops bounding slot count
at ``max_len × n_slots`` bf16 (VERDICT r2 next #9: an 8B model's bf16
cache is ~2 GB/slot at 8k context; int8 + scales is ~1.2 GB). Scale
layout is ``[n_layers, n_slots, n_kv_heads, 8, max_len]`` — the scale
vector a kernel needs per kv block is positions-along-lanes, and the
8-wide replicated sublane axis makes the block ``(8, block_k)``, an
exact f32 VMEM tile (a bare ``[block_k]`` vector block cannot tile).

The cache is a functional pytree; the model's prefill/decode steps return
updated buffers which XLA aliases in place when the jitted step donates them
(``gofr_tpu/serving/engine.py`` does).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp


class KVCache(NamedTuple):
    k: jnp.ndarray  # [layers, slots, kv_heads, max_len, head_dim]
    v: jnp.ndarray
    lengths: jnp.ndarray  # [slots] int32 — tokens currently in each slot
    # int8 mode only: per-position absmax scales, sublane-replicated ×8
    # ([layers, slots, kv_heads, 8, max_len] f32); None in bf16 mode.
    k_s: Optional[jnp.ndarray] = None
    v_s: Optional[jnp.ndarray] = None

    @classmethod
    def create(
        cls,
        n_layers: int,
        n_slots: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        quant: str = "",
    ) -> "KVCache":
        shape = (n_layers, n_slots, n_kv_heads, max_len, head_dim)
        if (quant or "").lower() == "int8":
            sshape = (n_layers, n_slots, n_kv_heads, 8, max_len)
            return cls(
                k=jnp.zeros(shape, dtype=jnp.int8),
                v=jnp.zeros(shape, dtype=jnp.int8),
                lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
                k_s=jnp.ones(sshape, dtype=jnp.float32),
                v_s=jnp.ones(sshape, dtype=jnp.float32),
            )
        if quant:
            raise ValueError(f"unsupported KV quant mode {quant!r} (int8 only)")
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((n_slots,), dtype=jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_s is not None

    @property
    def n_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    def hbm_bytes(self) -> int:
        total = self.k.size * self.k.dtype.itemsize * 2
        if self.k_s is not None:
            total += self.k_s.size * self.k_s.dtype.itemsize * 2
        return int(total)


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Absmax-int8 quantize K/V rows over the trailing head_dim axis.

    x: [..., head_dim] → (q int8 same shape, scale f32 [...]) — one scalar
    scale per (token, head) row, the standard KV-cache granularity.
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def fake_quantize_kv(x: jnp.ndarray) -> jnp.ndarray:
    """Quantize → dequantize (same dtype out). The split decode/verify
    paths attend a token's K/V BEFORE it is committed to an int8 cache;
    running the fresh values through the quantizer first makes what is
    attended bit-identical to what later steps will read back — and
    re-quantizing the result at commit time reproduces the same int8
    (the max element maps to exactly ±127, so the absmax scale is a
    fixed point)."""
    q, scale = quantize_kv(x)
    return (q.astype(jnp.float32) * scale[..., None]).astype(x.dtype)
