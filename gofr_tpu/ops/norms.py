"""Normalization ops.

Computed in f32 regardless of input dtype (bf16-safe), cast back on exit so
XLA fuses the whole op into neighboring matmuls.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    offset: float = 0.0,
) -> jnp.ndarray:
    """RMSNorm (Llama-family). ``weight`` has shape [d_model].

    ``offset``: Gemma stores its scale as ``w`` with the forward applying
    ``(offset + w)`` (offset=1.0), so identity is w=0 there.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (out * (weight.astype(jnp.float32) + offset)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """LayerNorm (BERT-family)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (out * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
