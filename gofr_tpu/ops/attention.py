"""Attention ops: prefill (causal, full-sequence) and decode (one query token
against a KV cache slice).

Dense baseline implementations in pure jnp — static shapes, f32 softmax
accumulation, GQA via head-group broadcasting — with layouts chosen so the
pallas flash kernels (``gofr_tpu/ops/pallas/``) are drop-in replacements on
TPU. The dispatch helpers pick the kernel path when available.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# GOFR_TPU_FLASH: "1" force kernels (interpret-mode off-TPU), "0" force
# dense, unset/"auto" → kernels on TPU backends only.
_FLASH_ENV = os.environ.get("GOFR_TPU_FLASH", "auto")
# GOFR_TPU_FLASH_DECODE: overrides GOFR_TPU_FLASH for DECODE attention
# only. The decode kernel launches grid (slots × kv_heads × kv_blocks)
# tiny programs per layer (length-skipping, O(true context) HBM reads);
# the dense path is one fused XLA op reading the full max_len cache.
# Which wins is a measured trade (per-program overhead vs full-length
# reads) — this knob lets the bench A/B it on hardware.
_FLASH_DECODE_ENV = os.environ.get("GOFR_TPU_FLASH_DECODE", "")
if _FLASH_DECODE_ENV not in ("", "0", "1"):
    raise ValueError(
        'GOFR_TPU_FLASH_DECODE must be "1", "0", or unset, got '
        f"{_FLASH_DECODE_ENV!r}"
    )
# GOFR_TPU_DECODE_BLOCK_K: kv block size for the decode kernel (default
# 256); bigger blocks → fewer grid programs, less length-skip precision.
try:
    _DECODE_BLOCK_K = int(os.environ.get("GOFR_TPU_DECODE_BLOCK_K", "256"))
    if _DECODE_BLOCK_K <= 0:
        raise ValueError
except ValueError:
    raise ValueError(
        "GOFR_TPU_DECODE_BLOCK_K must be a positive integer, got "
        f"{os.environ.get('GOFR_TPU_DECODE_BLOCK_K')!r}"
    ) from None


def _flash_enabled() -> bool:
    if _FLASH_ENV == "1":
        return True
    if _FLASH_ENV == "0":
        return False
    return jax.default_backend() == "tpu"


def _flash_decode_enabled() -> bool:
    if _FLASH_DECODE_ENV == "1":
        return True
    if _FLASH_DECODE_ENV == "0":
        return False
    return _flash_enabled()


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _effective_window(window: int, k_cache: jnp.ndarray, block_table) -> int:
    """0 when the sliding window cannot bind within the cache capacity.

    Contiguous caches are [b, KV, max_len, hd] (capacity = shape[2]); a
    paged pool is [n_blocks, KV, block, hd] where shape[2] is the BLOCK
    axis — capacity is the table's row length × block.
    """
    if not window:
        return 0
    if block_table is None:
        capacity = k_cache.shape[2]
    else:
        capacity = block_table.shape[1] * k_cache.shape[2]
    return 0 if window >= capacity else window


def _repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[b, s, kv_heads, hd] → [b, s, kv_heads*n_rep, hd] (GQA broadcast)."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    mask: jnp.ndarray | None = None,
    lengths: jnp.ndarray | None = None,
    scale: float | None = None,
    kernel: bool | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Full-sequence attention (prefill / encoder).

    window: sliding-window attention (Mistral) — each query attends only
    the last ``window`` keys (positions in (q_pos-window, q_pos]); 0 =
    full. Honored on both paths (the kernel masks in-kernel and skips
    kv blocks wholly below the window).
    q: [b, s_q, n_heads, hd]; k, v: [b, s_kv, n_kv_heads, hd].
    mask: optional [b, s_q, s_kv] additive-validity bool mask (True = attend).
    lengths: optional [b] valid key-prefix lengths (right-padded batches) —
    unlike ``mask`` this KEEPS the flash-kernel path (the kernel masks and
    skips kv blocks per row in-kernel; serving prefill uses this).
    kernel: None → auto (pallas flash kernel on TPU when no custom mask);
    the kernel path is differentiable (backward recomputes densely).
    """
    if mask is not None and lengths is not None:
        raise ValueError("pass either mask or lengths, not both")
    if window and window >= k.shape[1]:
        window = 0  # cannot bind: plain causal
    if window and not causal:
        raise ValueError("window requires causal attention")
    if kernel is None:
        kernel = _flash_enabled() and mask is None
    if kernel and mask is None:
        if lengths is not None:
            # Serving prefill (no grad) — call the kernel directly.
            from gofr_tpu.ops.pallas import flash_attention

            return flash_attention(
                q, k, v, lengths, causal=causal, scale=scale,
                window=window, interpret=_interpret(),
            )
        return _flash_attention_ad(q, k, v, causal, scale, window)
    b, s_q, n_heads, hd = q.shape
    s_kv, n_kv = k.shape[1], k.shape[2]
    n_rep = n_heads // n_kv
    if scale is None:
        scale = hd**-0.5
    if lengths is not None:
        mask = jnp.broadcast_to(
            (jnp.arange(s_kv)[None, :] < lengths[:, None])[:, None, :],
            (b, s_q, s_kv),
        )

    # Grouped-head formulation: no materialized KV repeat (HBM-friendly) and
    # the kv-head axis keeps one consistent tp sharding end to end.
    qg = q.reshape(b, s_q, n_kv, n_rep, hd)
    scores = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k, preferred_element_type=jnp.float32
    ) * scale  # [b, kv, rep, s_q, s_kv]

    if causal:
        # Offset so the last query attends to all keys (s_kv >= s_q case).
        q_pos = jnp.arange(s_q)[:, None] + (s_kv - s_q)
        causal_mask = jnp.arange(s_kv)[None, :] <= q_pos
        if window:
            causal_mask &= jnp.arange(s_kv)[None, :] > q_pos - window
        scores = jnp.where(causal_mask[None, None, None], scores, NEG_INF)
    elif window:
        raise ValueError("window requires causal attention")
    if mask is not None:
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs, v)
    return out.reshape(b, s_q, n_heads, hd)


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_new: jnp.ndarray | None = None,
    v_new: jnp.ndarray | None = None,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    block_table: jnp.ndarray | None = None,
    scale: float | None = None,
    kernel: bool | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Single-token decode attention against per-slot caches.

    window: sliding-window (Mistral) — the query attends only the last
    ``window`` positions including itself; 0 = full. Both paths honor
    it (the kernel masks in-kernel and skips out-of-window blocks).
    q: [b, n_heads, hd] (one query per sequence);
    k_cache, v_cache: [b, n_kv_heads, max_len, hd] (heads-major — the
    TPU-native cache layout, see ``ops/kv_cache.py``);
    lengths: [b] valid prefix length per slot. Two calling conventions:

    * ``k_new is None`` — the new token's K/V is already written in the
      cache at position lengths-1 (lengths INCLUDES it);
    * ``k_new``/``v_new`` given (``[b, n_kv, hd]``, same dtype as q) —
      the current token's K/V is attended SPLIT from the cache (online-
      softmax merge) and ``lengths`` counts only the cache prefix. This
      is the serving decode path: keeping the cache read-only inside the
      per-layer scan lets one scatter commit every layer's token per
      step, instead of the full cache round-tripping through scan ys
      (measured 11 ms/step of pure copy traffic on llama-1b at 32
      slots — scripts/tpu_probe.py).

    k_scale/v_scale: int8-cache mode — per-position absmax scales
    ``[b, n_kv, 8, max_len]`` (sublane-replicated, ``ops/kv_cache.py``);
    ``k_new``/``v_new`` stay bf16 (quantization happens at commit).
    kernel: None → auto (pallas flash-decode kernel on TPU; override with
    GOFR_TPU_FLASH_DECODE / GOFR_TPU_DECODE_BLOCK_K).
    """
    if (k_new is None) != (v_new is None):
        raise ValueError("pass k_new and v_new together")
    # A window that cannot bind is dropped (capacity-aware: a paged
    # pool's shape[2] is the BLOCK axis, not capacity). A BINDING window
    # keeps the kernel path — flash_decode masks it in-kernel and skips
    # whole blocks below the window (O(window) HBM reads, vs the dense
    # paged fallback's per-step full gather).
    window = _effective_window(window, k_cache, block_table)
    if kernel is None:
        kernel = _flash_decode_enabled()
        if (
            kernel
            and _FLASH_DECODE_ENV == ""
            and _FLASH_ENV in ("", "auto")
            and block_table is None
            and not window
        ):
            # Measured auto heuristic (BASELINE.md round 3): at short
            # max_len ONE fused dense op beats the kernel's grid of tiny
            # programs (llama-1b/1024: 2.4 vs 5.1 ms per stack; engine
            # 2421 vs 1931 tok/s); length-skipping only pays once the
            # full-length reads the dense path can't skip get big. The
            # paged pool always takes the kernel — its dense fallback
            # must materialize a gather first — and so does a binding
            # window (the kernel reads only the window's blocks).
            kernel = k_cache.shape[2] > 2048
    if kernel:
        from gofr_tpu.ops.pallas import flash_decode

        return flash_decode(
            q, k_cache, v_cache, lengths, k_new=k_new, v_new=v_new,
            k_scale=k_scale, v_scale=v_scale, block_table=block_table,
            scale=scale, block_k=_DECODE_BLOCK_K, window=window,
            interpret=_interpret(),
        )
    if block_table is not None:
        # Paged pool + dense fallback: gather each row's blocks into a
        # contiguous view, then fall through to the regular dense math
        # (the kernel path above indexes the pool in place instead).
        from gofr_tpu.ops.kv_cache import paged_view

        k_cache, v_cache, k_scale, v_scale = paged_view(
            block_table, k_cache, v_cache, jnp.arange(q.shape[0]),
            k_scale, v_scale,
        )
    n_heads = q.shape[1]
    n_kv = k_cache.shape[1]
    n_rep = n_heads // n_kv
    if scale is None:
        scale = q.shape[-1] ** -0.5

    # Group query heads by their KV head: [b, kv, rep, hd].
    b, max_len = k_cache.shape[0], k_cache.shape[2]
    qg = q.reshape(b, n_kv, n_rep, -1)

    quant = k_scale is not None
    if quant:  # int8 cache: dequant via score/prob scaling, not the cache
        k_cache = k_cache.astype(q.dtype)
        v_cache = v_cache.astype(q.dtype)
    scores = jnp.einsum(
        "bgrd,bgkd->bgrk", qg, k_cache, preferred_element_type=jnp.float32
    ) * scale  # [b, kv, rep, max_len]
    if quant:
        scores = scores * k_scale[:, :, 0, None, :]

    valid = jnp.arange(max_len)[None, :] < lengths[:, None]  # [b, max_len]
    if window:
        # Query position: ``lengths`` (split path — the new token) or
        # ``lengths-1`` (already-written convention). Keys must sit in
        # (q_pos - window, q_pos].
        q_pos = lengths if k_new is not None else lengths - 1
        valid &= jnp.arange(max_len)[None, :] > (q_pos - window)[:, None]
    scores = jnp.where(valid[:, None, None], scores, NEG_INF)

    if k_new is None:
        probs = jax.nn.softmax(scores, axis=-1)
        if quant:
            probs = probs * v_scale[:, :, 0, :][:, :, None, :]
        out = jnp.einsum(
            "bgrk,bgkd->bgrd", probs.astype(q.dtype), v_cache
        )
        return out.reshape(b, n_heads, -1)

    # Split path: merge the current token's (always-valid) score into the
    # cache-prefix softmax without writing it to the cache first.
    s_new = jnp.einsum(
        "bgrd,bgd->bgr", qg, k_new, preferred_element_type=jnp.float32
    ) * scale  # [b, kv, rep]
    m = jnp.maximum(jnp.max(scores, axis=-1), s_new)  # [b, kv, rep]
    e_c = jnp.exp(scores - m[..., None])  # [b, kv, rep, max_len]
    e_n = jnp.exp(s_new - m)  # [b, kv, rep]
    denom = jnp.sum(e_c, axis=-1) + e_n
    if quant:
        e_c = e_c * v_scale[:, :, 0, :][:, :, None, :]
    out = jnp.einsum("bgrk,bgkd->bgrd", e_c.astype(q.dtype), v_cache)
    out = out + e_n[..., None].astype(q.dtype) * v_new[:, :, None, :]
    out = out / denom[..., None].astype(q.dtype)
    return out.reshape(b, n_heads, -1)


def verify_chunk_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    prev_lengths: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Speculative-verify attention: ``c`` fresh tokens per slot attend the
    cache prefix PLUS themselves (causal within the chunk) — the cache
    stays read-only, mirroring ``decode_attention``'s ``k_new`` split path
    so rejected drafts never have to be rolled back out of the cache.

    q: [b, c, n_heads, hd] (position j is global position
    prev_lengths[b]+j); k_cache/v_cache: [b, n_kv, max_len, hd];
    prev_lengths: [b] valid cache prefix; k_new/v_new: [b, c, n_kv, hd]
    (the chunk's own K/V, bf16); k_scale/v_scale: int8-cache scales
    [b, n_kv, 8, max_len]. Returns [b, c, n_heads, hd].
    """
    b, c, n_heads, hd = q.shape
    n_kv, max_len = k_cache.shape[1], k_cache.shape[2]
    if window and window >= max_len + c:
        window = 0  # cannot bind: skip the mask work
    rep = n_heads // n_kv
    if scale is None:
        scale = hd**-0.5
    quant = k_scale is not None
    if quant:
        k_cache = k_cache.astype(q.dtype)
        v_cache = v_cache.astype(q.dtype)
    qg = q.reshape(b, c, n_kv, rep, hd)

    # Cache-prefix scores: [b, kv, rep, c, max_len], valid keys < length.
    s_c = jnp.einsum(
        "bcgrd,bgkd->bgrck", qg, k_cache, preferred_element_type=jnp.float32
    ) * scale
    if quant:
        s_c = s_c * k_scale[:, :, 0, :][:, :, None, None, :]
    valid = jnp.arange(max_len)[None, :] < prev_lengths[:, None]  # [b, T]
    valid = jnp.broadcast_to(valid[:, None, :], (b, c, max_len))
    if window:
        # Query j sits at global prev_lengths+j; cache keys must be in
        # (q_pos - window, q_pos].
        q_pos = prev_lengths[:, None] + jnp.arange(c)[None, :]  # [b, c]
        valid = valid & (
            jnp.arange(max_len)[None, None, :]
            > (q_pos - window)[:, :, None]
        )
    # valid is [b, c, max_len]; scores are [b, kv, rep, c, max_len].
    s_c = jnp.where(valid[:, None, None, :, :], s_c, NEG_INF)

    # In-chunk scores: [b, kv, rep, c, c], causal (key pos <= query pos).
    s_n = jnp.einsum(
        "bcgrd,btgd->bgrct", qg, k_new, preferred_element_type=jnp.float32
    ) * scale
    causal = jnp.arange(c)[None, :] <= jnp.arange(c)[:, None]  # [c_q, c_k]
    if window:
        causal = causal & (
            jnp.arange(c)[None, :] > jnp.arange(c)[:, None] - window
        )
    s_n = jnp.where(causal[None, None, None], s_n, NEG_INF)

    # Merged softmax over both key sets.
    m = jnp.maximum(jnp.max(s_c, axis=-1), jnp.max(s_n, axis=-1))
    e_c = jnp.exp(s_c - m[..., None])
    e_n = jnp.exp(s_n - m[..., None])
    denom = jnp.sum(e_c, axis=-1) + jnp.sum(e_n, axis=-1)
    if quant:
        e_c = e_c * v_scale[:, :, 0, :][:, :, None, None, :]
    out = jnp.einsum("bgrck,bgkd->bgrcd", e_c.astype(q.dtype), v_cache)
    out = out + jnp.einsum(
        "bgrct,btgd->bgrcd", e_n.astype(q.dtype), v_new
    )
    out = out / denom[..., None].astype(q.dtype)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, c, n_heads, hd)


def cache_chunk_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    slots: jnp.ndarray,
    starts: jnp.ndarray,
    lens: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    block_table: jnp.ndarray | None = None,
    scale: float | None = None,
    kernel: bool | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Chunked-prefill attention: a [P, c] chunk of queries per row attends
    to its slot's cache prefix [0, starts[p]+t] (causal at global
    positions). The chunk's K/V must already be written into the cache.

    q: [P, c, n_heads, hd]; caches: [S, n_kv, max_len, hd] (heads-major);
    slots/starts/lens: [P] int32 (lens = valid tokens in this chunk);
    k_scale/v_scale: int8-cache scales [S, n_kv, 8, max_len].
    block_table ([S, max_blocks] int32, paged): the caches are a pool
    [n_blocks, n_kv, block, hd]; the kernel indexes it through the table
    in place, while the dense path gathers each row's contiguous view
    (the CPU/tests fallback). Rows with t >= lens[p] return 0.
    kernel: None → auto (pallas on TPU).
    """
    window = _effective_window(window, k_cache, block_table)
    if kernel is None:
        kernel = _flash_enabled()
    if kernel:
        from gofr_tpu.ops.pallas import flash_cache_attention

        return flash_cache_attention(
            q, k_cache, v_cache, slots, starts, lens, k_scale=k_scale,
            v_scale=v_scale, block_table=block_table, scale=scale,
            window=window, interpret=_interpret(),
        )
    pre_gathered = False
    if block_table is not None:
        from gofr_tpu.ops.kv_cache import paged_view

        k_cache, v_cache, k_scale, v_scale = paged_view(
            block_table, k_cache, v_cache, slots, k_scale, v_scale
        )
        pre_gathered = True  # views are already per-row: skip the gather
    P, c, n_heads, hd = q.shape
    n_kv, max_len = k_cache.shape[1], k_cache.shape[2]
    rep = n_heads // n_kv
    if scale is None:
        scale = hd**-0.5
    quant = k_scale is not None
    if pre_gathered:
        ck, cv = k_cache, v_cache
    else:
        ck = k_cache[slots]  # [P, KV, max_len, hd]
        cv = v_cache[slots]
    if quant:  # int8 cache: dequant via score/prob scaling, not the cache
        ck = ck.astype(q.dtype)
        cv = cv.astype(q.dtype)
    qg = q.reshape(P, c, n_kv, rep, hd)
    scores = jnp.einsum(
        "pcgrd,pgkd->pgrck", qg, ck, preferred_element_type=jnp.float32
    ) * scale  # [P, KV, rep, c, max_len]
    if quant:
        ksl = k_scale if pre_gathered else k_scale[slots]
        scores = scores * ksl[:, :, 0, :][:, :, None, None, :]
    t = jnp.arange(c)
    pos = starts[:, None] + t[None, :]  # [P, c] global query positions
    valid = jnp.arange(max_len)[None, None, :] <= pos[:, :, None]
    if window:
        valid &= (
            jnp.arange(max_len)[None, None, :]
            > (pos - window)[:, :, None]
        )
    valid = jnp.logical_and(valid, (t[None, :] < lens[:, None])[:, :, None])
    scores = jnp.where(valid[:, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if quant:
        vsl = v_scale if pre_gathered else v_scale[slots]
        probs = probs * vsl[:, :, 0, :][:, :, None, None, :]
    out = jnp.einsum("pgrck,pgkd->pcgrd", probs.astype(q.dtype), cv)
    out = jnp.where(
        (t[None, :] < lens[:, None])[:, :, None, None, None], out, 0.0
    )
    return out.reshape(P, c, n_heads, hd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_ad(q, k, v, causal, scale, window=0):
    """Flash forward, dense-recompute backward.

    pallas_call has no reverse-mode rule, so the VJP re-derives gradients
    from the dense formulation — training memory matches the dense path
    while inference (no grad) gets the O(s) kernel. ``window`` threads
    through both directions (windowed-model training stays exact).
    """
    from gofr_tpu.ops.pallas import flash_attention

    return flash_attention(
        q, k, v, causal=causal, scale=scale, window=window,
        interpret=_interpret(),
    )


def _flash_ad_fwd(q, k, v, causal, scale, window=0):
    return _flash_attention_ad(q, k, v, causal, scale, window), (q, k, v)


def _flash_ad_bwd(causal, scale, window, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: attention(
            q, k, v, causal=causal, scale=scale, kernel=False,
            window=window,
        ),
        q, k, v,
    )
    return vjp(g)


_flash_attention_ad.defvjp(_flash_ad_fwd, _flash_ad_bwd)
