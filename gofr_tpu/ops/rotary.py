"""Rotary position embeddings (RoPE), Llama-style half-rotation layout."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 500000.0) -> tuple:
    """Precompute (cos, sin) tables of shape [max_len, head_dim//2] in f32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_len, head_dim//2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, positions: jnp.ndarray
) -> jnp.ndarray:
    """Apply RoPE.

    x: [batch, seq, heads, head_dim]; positions: [batch, seq] absolute
    positions (gathered into the tables — decode passes per-slot offsets).

    Partial rotary (GPT-NeoX ``rotary_pct``): the TABLE width defines the
    rotated subspace — tables built with ``rope_frequencies(nd, ...)``
    for nd < head_dim rotate only the first nd dims and pass the rest
    through unchanged.
    """
    dtype = x.dtype
    cos_p = cos[positions][:, :, None, :]  # [b, s, 1, nd/2]
    sin_p = sin[positions][:, :, None, :]
    nd = 2 * cos.shape[-1]
    rot, rest = x[..., :nd], x[..., nd:]
    x1, x2 = jnp.split(rot.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], axis=-1
    ).astype(dtype)
    if rest.shape[-1]:
        out = jnp.concatenate([out, rest], axis=-1)
    return out
