"""Int8 weight-only quantization for serving.

Decode is HBM-bandwidth-bound on weight reads (every step streams the
full parameter set); storing matmul weights as int8 with per-output-channel
f32 scales halves that traffic. Dequantization happens inside the jitted
step — ``dequant = q.astype(bf16) * scale`` immediately feeding an einsum —
so XLA fuses it into the matmul loop and HBM sees only int8 bytes plus a
tiny scale vector.

Representation: a :class:`Q8` pytree node ``(q: int8, s: f32)`` replacing
the weight leaf. The model's einsum helper (``models/transformer.py
_wein``) dequantizes transparently, so the same forward serves bf16 and
int8 params. Embeddings stay bf16 (gathers only touch the rows they need);
norms/scales are tiny and stay bf16.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class Q8(NamedTuple):
    """Int8 weight + per-output-channel scale (broadcastable to q.shape)."""

    q: jnp.ndarray  # int8, same shape as the original weight
    s: jnp.ndarray  # f32, shape = 1s except the channel (last) axis

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # for code asking "what compute dtype is this"
        return jnp.bfloat16


class Q4(NamedTuple):
    """Int4 weight + group-wise scales (W4A16).

    ``q``: uint8 with TWO 4-bit values (two's-complement nibbles) packed
    along the contraction axis — ``[..., D/2, out]`` for an original
    ``[..., D, out]`` weight. Explicit nibble packing instead of XLA's
    native s4: same ½-byte/elem HBM footprint, but the arrays are plain
    uint8 everywhere outside the fused unpack — s4 layouts trip backend
    bugs (the axon relay's ``device_put`` re-layout of S4 recursed
    fatally) and s4 support is emulated on most backends anyway.
    ``s``: f32 ``[..., G, 1, out]`` — one scale per ``group`` contraction
    rows per output channel (group-wise absmax keeps 4-bit quality;
    per-column int4 is too coarse for real weights). Weight HBM is ~¼ of
    bf16 — an 8B model stores in ~4 GB.
    """

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):  # logical (unpacked) shape
        lead, (d2, o) = self.q.shape[:-2], self.q.shape[-2:]
        return (*lead, d2 * 2, o)

    @property
    def dtype(self):
        return jnp.bfloat16


def quantize_array(w: jnp.ndarray) -> Q8:
    """Absmax int8 quantization reducing ONLY the contraction axis.

    Every matmul weight in the model — stacked or not, dense or MoE —
    contracts its second-to-last axis (wq [L, D, H*hd], w_down [L, E, F, D],
    lm_head [D, V], …), so scales keep per-layer / per-expert / per-channel
    resolution with one rule: absmax over ``axis=-2``.
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return Q8(q=q, s=scale.astype(jnp.float32))


def quantize_array4(w: jnp.ndarray, group: int = 128) -> Q4:
    """Group-wise absmax int4 over the contraction (-2) axis, nibble-
    packed into uint8 (two values per byte along that axis).

    ``group`` shrinks to the axis size when it doesn't divide it (tiny
    test models); real model dims are multiples of 128. The contraction
    axis must be even (every real transformer dim is).
    """
    D = w.shape[-2]
    if D % 2:
        raise ValueError(f"int4 nibble packing needs an even contraction "
                         f"axis, got {D}")
    if D % group:
        group = D
    G = D // group
    lead = w.shape[:-2]
    wf = w.astype(jnp.float32).reshape(*lead, G, group, w.shape[-1])
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)  # [.., G, 1, O]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -7, 7).astype(jnp.int32)
    q = q.reshape(*lead, D, w.shape[-1])
    nib = jnp.where(q < 0, q + 16, q).astype(jnp.uint8)  # two's complement
    packed = (nib[..., 0::2, :] << 4) | nib[..., 1::2, :]
    return Q4(q=packed, s=scale.astype(jnp.float32))


def dequantize(w: Any, dtype=jnp.bfloat16) -> jnp.ndarray:
    if isinstance(w, Q8):
        return (w.q.astype(jnp.float32) * w.s).astype(dtype)
    if isinstance(w, Q4):
        lead, (D2, O) = w.q.shape[:-2], w.q.shape[-2:]
        D = D2 * 2
        # Unpack nibbles (hi = even rows, lo = odd) and sign-extend —
        # elementwise ops XLA fuses into the consuming matmul's read.
        hi = (w.q >> 4).astype(jnp.int32)
        lo = (w.q & 0xF).astype(jnp.int32)
        n = jnp.stack([hi, lo], axis=-2)  # [..., D/2, 2, O]
        n = jnp.where(n > 7, n - 16, n).reshape(*lead, D, O)
        G = w.s.shape[-3]
        wf = n.astype(jnp.float32).reshape(*lead, G, D // G, O) * w.s
        return wf.reshape(*lead, D, O).astype(dtype)
    return w


# Weight leaves worth quantizing: the big matmul weights. Embeddings
# (gather), norms (tiny), and the MoE router (tiny AND routing-sensitive:
# a flipped top-k from quantization error changes which experts run)
# stay in bf16.
_QUANT_KEYS = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"
}


def _quant_fn(mode: str):
    if mode == "int8":
        return quantize_array
    if mode == "int4":
        return quantize_array4
    raise ValueError(f"unsupported quant mode {mode!r} (int8 or int4)")


def quantize_params(params: dict, mode: str = "int8") -> dict:
    """Quantize a transformer param tree's matmul weights (Q8 or Q4)
    in place (returns a new tree; other leaves pass through untouched)."""
    quant = _quant_fn(mode)
    out = dict(params)
    out["layers"] = {
        k: (quant(v) if k in _QUANT_KEYS else v)
        for k, v in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quant(params["lm_head"])
    return out


def q8_spec(spec) -> Q8:
    """The Q8 PartitionSpec pair for a weight whose bf16 spec is ``spec``.

    ``q`` keeps the weight's sharding (same shape). ``s`` has extent 1 on
    the contraction (-2) axis, so that entry must be unsharded; every other
    axis (leading layer/pp axes, the output-channel axis) keeps the
    weight's sharding — the scale vector shards WITH its output channels,
    which is what lets int8 compose with a tp mesh (VERDICT r2 next #2).
    """
    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    if len(entries) >= 2:
        entries[-2] = None
    return Q8(q=spec, s=P(*entries))


def q4_spec(spec) -> Q4:
    """Q4 PartitionSpec pair: ``q`` keeps the weight's sharding; the
    group-wise scale ``[..., G, 1, out]`` replicates its G and unit axes
    (G may not divide tp for small models; scales are tiny) and keeps the
    output-channel sharding."""
    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    return Q4(q=spec, s=P(*entries[:-2], None, None, entries[-1]))


def quantized_param_specs(specs: dict, mode: str = "int8") -> dict:
    """Map a bf16 param-spec tree (``transformer_param_specs``) to the spec
    tree of ``quantize_params(params, mode)``: quantized leaves become
    Q8/Q4 spec pairs, everything else passes through."""
    _quant_fn(mode)  # validate
    qspec = q8_spec if mode == "int8" else q4_spec
    out = dict(specs)
    out["layers"] = {
        k: (qspec(v) if k in _QUANT_KEYS else v)
        for k, v in specs["layers"].items()
    }
    if "lm_head" in specs:
        out["lm_head"] = qspec(specs["lm_head"])
    return out


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes as stored (int8 → 1 B/elem; int4 leaves are
    nibble-packed uint8, so the generic itemsize path already counts
    them at ½ B per logical element)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return int(total)
