"""Int8 weight-only quantization for serving.

Decode is HBM-bandwidth-bound on weight reads (every step streams the
full parameter set); storing matmul weights as int8 with per-output-channel
f32 scales halves that traffic. Dequantization happens inside the jitted
step — ``dequant = q.astype(bf16) * scale`` immediately feeding an einsum —
so XLA fuses it into the matmul loop and HBM sees only int8 bytes plus a
tiny scale vector.

Representation: a :class:`Q8` pytree node ``(q: int8, s: f32)`` replacing
the weight leaf. The model's einsum helper (``models/transformer.py
_wein``) dequantizes transparently, so the same forward serves bf16 and
int8 params. Embeddings stay bf16 (gathers only touch the rows they need);
norms/scales are tiny and stay bf16.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class Q8(NamedTuple):
    """Int8 weight + per-output-channel scale (broadcastable to q.shape)."""

    q: jnp.ndarray  # int8, same shape as the original weight
    s: jnp.ndarray  # f32, shape = 1s except the channel (last) axis

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # for code asking "what compute dtype is this"
        return jnp.bfloat16


class Q4(NamedTuple):
    """Int4 weight + group-wise scales (W4A16).

    ``q``: int4 (XLA native s4, packed 2/byte in HBM), original shape.
    ``s``: f32 ``[..., G, 1, out]`` — one scale per ``group`` contraction
    rows per output channel (group-wise absmax keeps 4-bit quality;
    per-column int4 is too coarse for real weights). Weight HBM is ~¼ of
    bf16 — an 8B model stores in ~4 GB.
    """

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return jnp.bfloat16


def quantize_array(w: jnp.ndarray) -> Q8:
    """Absmax int8 quantization reducing ONLY the contraction axis.

    Every matmul weight in the model — stacked or not, dense or MoE —
    contracts its second-to-last axis (wq [L, D, H*hd], w_down [L, E, F, D],
    lm_head [D, V], …), so scales keep per-layer / per-expert / per-channel
    resolution with one rule: absmax over ``axis=-2``.
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return Q8(q=q, s=scale.astype(jnp.float32))


def quantize_array4(w: jnp.ndarray, group: int = 128) -> Q4:
    """Group-wise absmax int4 over the contraction (-2) axis.

    ``group`` shrinks to the axis size when it doesn't divide it (tiny
    test models); real model dims are multiples of 128.
    """
    D = w.shape[-2]
    if D % group:
        group = D
    G = D // group
    lead = w.shape[:-2]
    wf = w.astype(jnp.float32).reshape(*lead, G, group, w.shape[-1])
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)  # [.., G, 1, O]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -7, 7).astype(jnp.int4)
    return Q4(q=q.reshape(w.shape), s=scale.astype(jnp.float32))


def dequantize(w: Any, dtype=jnp.bfloat16) -> jnp.ndarray:
    if isinstance(w, Q8):
        return (w.q.astype(jnp.float32) * w.s).astype(dtype)
    if isinstance(w, Q4):
        lead, (D, O) = w.q.shape[:-2], w.q.shape[-2:]
        G = w.s.shape[-3]
        wf = w.q.astype(jnp.float32).reshape(*lead, G, D // G, O) * w.s
        return wf.reshape(w.q.shape).astype(dtype)
    return w


# Weight leaves worth quantizing: the big matmul weights. Embeddings
# (gather), norms (tiny), and the MoE router (tiny AND routing-sensitive:
# a flipped top-k from quantization error changes which experts run)
# stay in bf16.
_QUANT_KEYS = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"
}


def _quant_fn(mode: str):
    if mode == "int8":
        return quantize_array
    if mode == "int4":
        return quantize_array4
    raise ValueError(f"unsupported quant mode {mode!r} (int8 or int4)")


def quantize_params(params: dict, mode: str = "int8") -> dict:
    """Quantize a transformer param tree's matmul weights (Q8 or Q4)
    in place (returns a new tree; other leaves pass through untouched)."""
    quant = _quant_fn(mode)
    out = dict(params)
    out["layers"] = {
        k: (quant(v) if k in _QUANT_KEYS else v)
        for k, v in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quant(params["lm_head"])
    return out


def q8_spec(spec) -> Q8:
    """The Q8 PartitionSpec pair for a weight whose bf16 spec is ``spec``.

    ``q`` keeps the weight's sharding (same shape). ``s`` has extent 1 on
    the contraction (-2) axis, so that entry must be unsharded; every other
    axis (leading layer/pp axes, the output-channel axis) keeps the
    weight's sharding — the scale vector shards WITH its output channels,
    which is what lets int8 compose with a tp mesh (VERDICT r2 next #2).
    """
    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    if len(entries) >= 2:
        entries[-2] = None
    return Q8(q=spec, s=P(*entries))


def q4_spec(spec) -> Q4:
    """Q4 PartitionSpec pair: ``q`` keeps the weight's sharding; the
    group-wise scale ``[..., G, 1, out]`` replicates its G and unit axes
    (G may not divide tp for small models; scales are tiny) and keeps the
    output-channel sharding."""
    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    return Q4(q=spec, s=P(*entries[:-2], None, None, entries[-1]))


def quantized_param_specs(specs: dict, mode: str = "int8") -> dict:
    """Map a bf16 param-spec tree (``transformer_param_specs``) to the spec
    tree of ``quantize_params(params, mode)``: quantized leaves become
    Q8/Q4 spec pairs, everything else passes through."""
    _quant_fn(mode)  # validate
    qspec = q8_spec if mode == "int8" else q4_spec
    out = dict(specs)
    out["layers"] = {
        k: (qspec(v) if k in _QUANT_KEYS else v)
        for k, v in specs["layers"].items()
    }
    if "lm_head" in specs:
        out["lm_head"] = qspec(specs["lm_head"])
    return out


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes as stored (int8 → 1 B/elem, int4 → ½ B/elem
    — XLA packs s4 two per byte in HBM)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if leaf.dtype.name in ("int4", "uint4"):
            total += (leaf.size + 1) // 2
        else:
            total += leaf.size * leaf.dtype.itemsize
    return int(total)
