"""Int8 weight-only quantization for serving.

Decode is HBM-bandwidth-bound on weight reads (every step streams the
full parameter set); storing matmul weights as int8 with per-output-channel
f32 scales halves that traffic. Dequantization happens inside the jitted
step — ``dequant = q.astype(bf16) * scale`` immediately feeding an einsum —
so XLA fuses it into the matmul loop and HBM sees only int8 bytes plus a
tiny scale vector.

Representation: a :class:`Q8` pytree node ``(q: int8, s: f32)`` replacing
the weight leaf. The model's einsum helper (``models/transformer.py
_wein``) dequantizes transparently, so the same forward serves bf16 and
int8 params. Embeddings stay bf16 (gathers only touch the rows they need);
norms/scales are tiny and stay bf16.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class Q8(NamedTuple):
    """Int8 weight + per-output-channel scale (broadcastable to q.shape)."""

    q: jnp.ndarray  # int8, same shape as the original weight
    s: jnp.ndarray  # f32, shape = 1s except the channel (last) axis

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # for code asking "what compute dtype is this"
        return jnp.bfloat16


def quantize_array(w: jnp.ndarray) -> Q8:
    """Absmax int8 quantization reducing ONLY the contraction axis.

    Every matmul weight in the model — stacked or not, dense or MoE —
    contracts its second-to-last axis (wq [L, D, H*hd], w_down [L, E, F, D],
    lm_head [D, V], …), so scales keep per-layer / per-expert / per-channel
    resolution with one rule: absmax over ``axis=-2``.
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return Q8(q=q, s=scale.astype(jnp.float32))


def dequantize(w: Any, dtype=jnp.bfloat16) -> jnp.ndarray:
    if isinstance(w, Q8):
        return (w.q.astype(jnp.float32) * w.s).astype(dtype)
    return w


# Weight leaves worth quantizing: the big matmul weights. Embeddings (gather)
# and norms (tiny) stay in bf16.
_QUANT_KEYS = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router", "lm_head"
}


def quantize_params(params: dict) -> dict:
    """Quantize a transformer param tree's matmul weights to Q8 in place
    (returns a new tree; non-matmul leaves pass through untouched)."""
    out = dict(params)
    out["layers"] = {
        k: (quantize_array(v) if k in _QUANT_KEYS else v)
        for k, v in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_array(params["lm_head"])
    return out


def q8_spec(spec) -> Q8:
    """The Q8 PartitionSpec pair for a weight whose bf16 spec is ``spec``.

    ``q`` keeps the weight's sharding (same shape). ``s`` has extent 1 on
    the contraction (-2) axis, so that entry must be unsharded; every other
    axis (leading layer/pp axes, the output-channel axis) keeps the
    weight's sharding — the scale vector shards WITH its output channels,
    which is what lets int8 compose with a tp mesh (VERDICT r2 next #2).
    """
    from jax.sharding import PartitionSpec as P

    entries = list(spec)
    if len(entries) >= 2:
        entries[-2] = None
    return Q8(q=spec, s=P(*entries))


def quantized_param_specs(specs: dict) -> dict:
    """Map a bf16 param-spec tree (``transformer_param_specs``) to the spec
    tree of ``quantize_params(params)``: quantized leaves become Q8 spec
    pairs, everything else passes through."""
    out = dict(specs)
    out["layers"] = {
        k: (q8_spec(v) if k in _QUANT_KEYS else v)
        for k, v in specs["layers"].items()
    }
    if "lm_head" in specs:
        out["lm_head"] = q8_spec(specs["lm_head"])
    return out


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes as stored (int8 leaves count 1 byte/elem)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return int(total)
