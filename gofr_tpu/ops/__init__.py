"""TPU-first neural net ops (net-new; no reference analog — SURVEY §2.6).

Functional JAX ops designed for the MXU/XLA compilation model: static
shapes, fused elementwise tails, bf16 matmul paths with f32 accumulation,
and kernel-ready layouts (last dim a multiple of 128 where it matters).
"""

from gofr_tpu.ops.norms import rms_norm, layer_norm
from gofr_tpu.ops.rotary import apply_rope, rope_frequencies
from gofr_tpu.ops.attention import attention, decode_attention
from gofr_tpu.ops.kv_cache import KVCache
from gofr_tpu.ops.sampling import sample_logits
from gofr_tpu.ops.ring_attention import (
    context_parallel_attention,
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "context_parallel_attention",
    "ring_attention",
    "ulysses_attention",
    "rms_norm",
    "layer_norm",
    "apply_rope",
    "rope_frequencies",
    "attention",
    "decode_attention",
    "KVCache",
    "sample_logits",
]
