"""Context parallelism for long sequences: ring attention and Ulysses.

The reference framework has no sequence/tensor code at all (SURVEY.md §2.6,
§5 "Long-context"), so this subsystem is TPU-native net-new: it lets one
logical attention call run over a sequence sharded across an ICI mesh axis,
which is how the serving/training stack scales past single-chip HBM.

Two interchangeable schemes, both written as collectives *inside*
``jax.shard_map`` (so XLA lowers them onto ICI):

* **Ring attention** (`ring_attention`) — K/V blocks rotate around the mesh
  axis via ``lax.ppermute`` while each device keeps its resident Q block and
  folds every visiting K/V block into a numerically-stable online softmax
  (flash-style running max/sum in f32). Communication is overlap-friendly
  nearest-neighbour traffic; memory stays O(s/n) per device.
* **Ulysses** (`ulysses_attention`) — two ``lax.all_to_all`` reshards swap
  the sequence sharding for a head sharding, run ordinary (flash-kernel
  eligible) attention on the full sequence with ``heads/n`` local heads,
  and swap back. Cheaper compute, all-to-all traffic; needs heads % n == 0.

`context_parallel_attention` is the user-facing wrapper that builds the
``shard_map`` over a mesh axis and dispatches to either scheme.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gofr_tpu.ops.attention import NEG_INF, attention, _repeat_kv


def _grouped_scores(qg, k, scale):
    """qg: [b, sq, g, r, d] grouped queries; k: [b, sk, g, d] → f32 scores
    [b, g, r, sq, sk]."""
    return (
        jnp.einsum("bqgrd,bkgd->bgrqk", qg, k, preferred_element_type=jnp.float32)
        * scale
    )


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """Blockwise ring attention over a sharded sequence axis.

    Must be called inside ``shard_map`` with the sequence dimension of
    q/k/v sharded over ``axis_name``. Shapes per device:
    q: [b, s_loc, n_heads, hd]; k, v: [b, s_loc, n_kv_heads, hd].

    Equal-size sequence chunks are assumed (s_global = n * s_loc), chunk i
    living on mesh position i. Causal masking is done at global positions:
    query p attends key t iff t <= p.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_loc, n_heads, hd = q.shape
    n_kv = k.shape[2]
    n_rep = n_heads // n_kv
    if scale is None:
        scale = hd**-0.5

    qg = q.reshape(b, s_loc, n_kv, n_rep, hd)

    # Online-softmax state, all f32; pvary marks it device-varying over the
    # ring axis so the fori_loop carry type matches the per-step outputs.
    o = jnp.zeros((b, s_loc, n_kv, n_rep, hd), jnp.float32)
    m = jnp.full((b, n_kv, n_rep, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, n_kv, n_rep, s_loc), jnp.float32)
    o, m, l = (lax.pcast(x, axis_name, to="varying") for x in (o, m, l))

    perm = [(j, (j + 1) % n) for j in range(n)]
    local_pos = jnp.arange(s_loc)

    def step(t, carry):
        k_blk, v_blk, o, m, l = carry
        # After t rotations device `my_idx` holds chunk (my_idx - t) mod n.
        kv_idx = (my_idx - t) % n
        scores = _grouped_scores(qg, k_blk, scale)  # [b, g, r, sq, sk]
        if causal:
            q_pos = my_idx * s_loc + local_pos  # [sq]
            kv_pos = kv_idx * s_loc + local_pos  # [sk]
            mask = kv_pos[None, :] <= q_pos[:, None]  # [sq, sk]
            scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        blk_max = jnp.max(scores, axis=-1)  # [b, g, r, sq]
        m_new = jnp.maximum(m, blk_max)
        # Rows with no valid key yet keep m == NEG_INF; shift by a finite
        # max to avoid (-inf) - (-inf) = NaN in the exp argument.
        shift = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(scores - shift[..., None])  # [b, g, r, sq, sk]
        if causal:
            p = jnp.where(mask[None, None, None], p, 0.0)
        alpha = jnp.exp(m - shift)  # [b, g, r, sq]
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bgrqk,bkgd->bqgrd", p, v_blk.astype(jnp.float32))
        o = o * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        # The last iteration's rotation would be discarded — skip it (the
        # predicate is the loop counter, uniform across devices, so the
        # cond resolves identically everywhere).
        k_blk, v_blk = lax.cond(
            t < n - 1,
            lambda kv: tuple(lax.ppermute(a, axis_name, perm) for a in kv),
            lambda kv: kv,
            (k_blk, v_blk),
        )
        return k_blk, v_blk, o, m_new, l

    _, _, o, m, l = lax.fori_loop(0, n, step, (k, v, o, m, l))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, s_loc, n_heads, hd).astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    scale: float | None = None,
    kernel: bool | None = None,
) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Must be called inside ``shard_map`` with the sequence dimension sharded
    over ``axis_name``. Reshards seq-parallel → head-parallel, runs dense or
    flash attention on the full sequence, reshards back. Requires
    n_heads % axis_size == 0; GQA K/V heads are broadcast up when the KV
    head count does not divide the axis size.
    """
    n = lax.psum(1, axis_name)
    n_heads, n_kv = q.shape[2], k.shape[2]
    if n_heads % n:
        raise ValueError(f"ulysses: n_heads={n_heads} not divisible by axis={n}")
    if n_kv % n:
        # Broadcast grouped KV heads so the head axis splits evenly: the
        # minimal repeat that makes the KV head count a multiple of the
        # axis size (lcm-based), falling back to full MHA only when needed.
        import math

        rep = math.lcm(n_kv, n) // n_kv
        if (n_heads // n_kv) % rep:
            rep = n_heads // n_kv  # full MHA — rep must divide the group
        if (n_kv * rep) % n:
            raise ValueError(
                f"ulysses: cannot shard GQA kv_heads={n_kv} over axis={n}: "
                f"post-repeat head count {n_kv * rep} not divisible by the "
                f"axis size (pick cp such that lcm(n_kv, cp)/n_kv divides "
                f"n_heads/n_kv)"
            )
        k = _repeat_kv(k, rep)
        v = _repeat_kv(v, rep)

    a2a = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    q, k, v = a2a(q), a2a(k), a2a(v)  # [b, s_full, h/n, hd]
    out = attention(q, k, v, causal=causal, scale=scale, kernel=kernel)
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def context_parallel_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    impl: str = "ring",
    causal: bool = True,
    scale: float | None = None,
) -> jnp.ndarray:
    """Attention over a sequence sharded across ``mesh[axis_name]``.

    Takes/returns global arrays [b, s, h, hd]; s must divide evenly over
    the axis. ``impl``: "ring" (ppermute blocks) or "ulysses" (all-to-all
    head resharding).
    """
    if impl == "ring":
        inner = functools.partial(
            ring_attention, axis_name=axis_name, causal=causal, scale=scale
        )
    elif impl == "ulysses":
        inner = functools.partial(
            ulysses_attention, axis_name=axis_name, causal=causal, scale=scale
        )
    else:
        raise ValueError(f"unknown context-parallel impl {impl!r}")

    spec = P(None, axis_name, None, None)
    # Partial-manual: only the sequence axis goes manual; any other mesh
    # axes (dp/tp/pp) stay auto so GSPMD keeps sharding the body's einsums.
    return jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
    )(q, k, v)
