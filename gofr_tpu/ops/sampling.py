"""Token sampling: greedy / temperature / top-k / top-p inside jit
(static control flow — all branches computed, selected by where)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jnp.ndarray,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jnp.ndarray:
    """Sample token ids from [batch, vocab] logits.

    temperature == 0 → greedy. top_k/top_p filter before sampling. These are
    Python-static knobs: changing them recompiles, which is the right trade
    for a serving engine with a handful of sampling configs.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits.astype(jnp.float32) / temperature

    needs_sort = (top_k > 0 and top_k < logits.shape[-1]) or top_p < 1.0
    if needs_sort:
        # One descending sort shared by both filters.
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k > 0 and top_k < logits.shape[-1]:
            kth = sorted_logits[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p < 1.0:
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # Keep the smallest prefix with cumulative prob >= top_p.
            cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
