"""Pallas TPU kernels for the attention hot path.

Two kernels, both written grid-sequential in the canonical TPU style (the
kv axis is the innermost grid dimension; online-softmax state carries in
VMEM scratch across kv iterations):

* :func:`flash_attention` — causal prefill, O(s) memory, GQA-aware block
  index maps so KV blocks are fetched once per kv-head (not per q-head);
* :func:`flash_decode` — one query token per sequence against a paged slot
  KV cache with per-slot lengths prefetched to SMEM so fully-invalid KV
  blocks are skipped before their DMA cost is paid;
* :func:`flash_cache_attention` — chunked-prefill queries against the slot
  cache in place (one fixed-shape compile serves every prompt length).

All run under ``interpret=True`` on CPU, which is how the unit tests
exercise them without hardware.
"""

from gofr_tpu.ops.pallas.flash_attention import flash_attention
from gofr_tpu.ops.pallas.flash_decode import flash_decode
from gofr_tpu.ops.pallas.flash_prefill import flash_cache_attention

__all__ = ["flash_attention", "flash_cache_attention", "flash_decode"]
