"""Flash-decode kernel (pallas TPU): one query token per slot vs KV cache.

Decode attention is HBM-bandwidth-bound: the whole valid cache prefix is
read once per step. The win over the dense path is (a) per-slot lengths are
prefetched to SMEM (``PrefetchScalarGridSpec``) so KV blocks beyond a
slot's length are skipped — with continuous batching most slots are far
shorter than max_len, so skipped blocks are most blocks — and (b) the
online softmax never materialises [b, heads, max_len] score tensors in HBM.

The kv-head axis is a grid dimension (like the head axis in
``flash_attention``), so each grid step runs two plain
``[rep, hd] × [hd, block_k]``-shaped MXU matmuls — Mosaic does not lower
batched matmuls whose batch dims sit in different operand positions
("batch dims must be equal"), which is exactly what a fused
``[g, rep, hd] × [block_k, g, hd]`` contraction produces.

Cache layout is the engine's native heads-major ``[b, n_kv, max_len, hd]``
(``ops/kv_cache.py``): per-head blocks are then ``[block_k, hd]`` on the
last two dims, which tiles onto VMEM — a heads-minor layout would need
1-sized blocks on the second-to-last dim, which pallas cannot tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _window_lo(length, window, has_new):
    """First key position inside the sliding window (0 = unwindowed).

    The query sits at ``length`` (split path — the new token) or
    ``length - 1`` (already-written convention); valid keys are in
    ``(q_pos - window, q_pos]``.
    """
    q_pos = length if has_new else length - 1
    return jnp.maximum(0, q_pos - window + 1)


def _clamp_blk(ik, length, block_k, window=0, has_new=False):
    """kv block index clamped to the slot's VISIBLE range: at most the
    last valid block, and (windowed) at least the first block the
    sliding window reaches — out-of-range grid steps then re-"fetch" a
    visible block, which the pallas pipeline elides (same index → no new
    DMA), so skipped blocks cost no HBM bandwidth on either side."""
    hi = jnp.maximum(0, (length - 1) // block_k)
    if window:
        lo = _window_lo(length, window, has_new) // block_k
        return jnp.clip(ik, jnp.minimum(lo, hi), hi)
    return jnp.minimum(ik, hi)


def _kernel(*refs, scale, block_k, quant, has_new, paged, window):
    """Grid: (b, n_kv, kv_blocks); kv blocks innermost, state in scratch.

    quant (static): int8 cache mode — two extra scale refs follow v_ref
    (``[8, block_k]`` sublane-replicated, one scale per key position);
    scores multiply by the K scale after the q·k matmul, probs by the V
    scale before p·v, so dequantized K/V tensors never materialize and
    HBM streams int8.

    has_new (static): the current token's K/V (``[8, hd]`` sublane-
    replicated f32 refs after the scale refs) is merged into the online
    softmax at the finish step instead of being read from the cache —
    ``lengths`` then counts only the cache prefix. Lets the serving
    decode keep the cache read-only until one end-of-step commit.

    paged (static): a second prefetched scalar (the block table) follows
    ``lengths``; the kernel BODY is unchanged — the table acts entirely
    through the BlockSpec index_maps, which turn logical kv-block ``ik``
    into a pool block id, so the pool is read in place with no gather.
    """
    refs = list(refs)
    len_ref = refs.pop(0)
    if paged:
        refs.pop(0)  # block table: consumed by the index_maps only
    q_ref, k_ref, v_ref = refs[:3]
    rest = refs[3:]
    k_s_ref = v_s_ref = kn_ref = vn_ref = None
    if quant:
        k_s_ref, v_s_ref = rest[:2]
        rest = rest[2:]
    if has_new:
        kn_ref, vn_ref = rest[:2]
        rest = rest[2:]
    o_ref, acc_ref, m_ref, l_ref = rest
    ib = pl.program_id(0)
    ik = pl.program_id(2)
    length = len_ref[ib]

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    col0 = ik * block_k
    last_vis = jnp.maximum(0, (length - 1) // block_k)
    # Sliding window (static): keys below lo_pos are invisible; whole
    # blocks below it skip their body (their DMAs were already elided by
    # the index-map clamp).
    lo_pos = _window_lo(length, window, has_new) if window else 0
    visible = col0 < length
    if window:
        visible &= col0 + block_k > lo_pos

    @pl.when(visible)
    def _body():
        q = q_ref[0, 0]      # [rep, hd]
        k = k_ref[0, 0]      # [block_k, hd]
        v = v_ref[0, 0]
        rep = q.shape[0]
        if quant:
            k = k.astype(q.dtype)
            v = v.astype(jnp.bfloat16)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [rep, block_k]
        if quant:
            s = s * k_s_ref[0, 0][0:1, :]  # per-key-position K scale

        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (rep, block_k), 1)
        mask = cols < length
        if window:
            mask &= cols >= lo_pos
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [rep, 128] (value replicated over lanes)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new[:, :1]), 0.0)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        if quant:
            p = p * v_s_ref[0, 0][0:1, :]  # fold V scale into probs
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rep, hd]
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv

    @pl.when(ik == last_vis)
    def _finish():
        if has_new:
            # Merge the current token (always valid, bf16, unscaled) into
            # the running softmax, then normalize. With an empty prefix
            # (length 0: m=-inf, l=0) this reduces to attending the new
            # token alone — corr underflows to 0 cleanly.
            # f32 throughout: the refs are f32 (wrapper casts) — Mosaic
            # rejects mixed-dtype broadcasts in this tail block.
            q = q_ref[0, 0].astype(jnp.float32)  # [rep, hd]
            kn = kn_ref[0, 0][0:1, :]  # [1, hd] f32 (row 0 of the 8-replica)
            vn = vn_ref[0, 0][0:1, :]
            s_n = jax.lax.dot_general(
                q, kn, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [rep, 1]
            m_prev = m_ref[:, :1]
            m_new = jnp.maximum(m_prev, s_n)
            corr = jnp.exp(m_prev - m_new)
            e_n = jnp.exp(s_n - m_new)
            l = l_ref[:, :1] * corr + e_n
            acc = acc_ref[:] * corr + e_n * vn
            o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
        else:
            l = l_ref[:, :1]
            out = jnp.where(
                l > 0.0, acc_ref[:] / jnp.where(l > 0.0, l, 1.0), 0.0
            )
            o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "window", "interpret")
)
def flash_decode(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_new: jnp.ndarray | None = None,
    v_new: jnp.ndarray | None = None,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    block_table: jnp.ndarray | None = None,
    scale: float | None = None,
    block_k: int = 256,
    window: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Same contract as ``ops.attention.decode_attention``:

    q: [b, n_heads, hd]; caches: [b, n_kv, max_len, hd] (heads-major);
    lengths: [b] valid prefix — INCLUDES the current token (already
    written at lengths-1) when ``k_new`` is None, EXCLUDES it when
    ``k_new``/``v_new`` ([b, n_kv, hd] bf16) are given (split path: the
    new token merges in-kernel at the finish step).
    k_scale/v_scale: int8-cache per-position scales
    [b, n_kv, 8, max_len] (sublane-replicated, ``ops/kv_cache.py``).

    block_table ([b, max_blocks] int32, paged mode): caches are then a
    POOL [n_blocks, n_kv, block, hd] (scales [n_blocks, n_kv, 8, block])
    and the table maps each row's logical kv block onto a pool block —
    indexing happens in the BlockSpec index_maps, so the pool streams
    straight from HBM with no per-step gather. ``block_k`` is the pool's
    block size in that mode.

    window (static): sliding-window attention — the query attends only
    keys in ``(q_pos - window, q_pos]`` (``ops.attention`` convention);
    0 = full. Masked in-kernel, and blocks wholly below the window skip
    both their body and their DMA (the index-map clamp re-fetches a
    visible block, which the pipeline elides) — windowed decode reads
    O(window), not O(length), from HBM. Returns [b, n_heads, hd].
    """
    b, n_heads, hd = q.shape
    paged = block_table is not None
    n_kv = k_cache.shape[1]
    n_rep = n_heads // n_kv
    quant = k_scale is not None
    has_new = k_new is not None
    if scale is None:
        scale = hd**-0.5

    if paged:
        block_k = k_cache.shape[2]
        n_grid_blocks = block_table.shape[1]
        max_len = n_grid_blocks * block_k
    else:
        max_len = k_cache.shape[2]
        block_k = min(block_k, max_len)
        if max_len % block_k:
            pad = block_k - max_len % block_k
            cfg = [(0, 0), (0, 0), (0, pad), (0, 0)]
            k_cache = jnp.pad(k_cache, cfg)
            v_cache = jnp.pad(v_cache, cfg)
            if quant:
                scfg = [(0, 0), (0, 0), (0, 0), (0, pad)]
                k_scale = jnp.pad(k_scale, scfg)
                v_scale = jnp.pad(v_scale, scfg)
            max_len += pad
        n_grid_blocks = max_len // block_k

    # Clamp the kv block index to the slot's last valid block: grid
    # steps beyond a short slot's length re-"fetch" the same block,
    # which the pallas pipeline elides (same index → no new DMA) —
    # this is where the SMEM-prefetched lengths actually save HBM
    # bandwidth, not just compute. Paged mode adds the table lookup on
    # top: the clamped LOGICAL block resolves to a pool block id.
    if paged:
        def kv_idx(ib, ig, ik, lens, bt):
            blk = _clamp_blk(ik, lens[ib], block_k, window, has_new)
            return (bt[ib, blk], ig, 0, 0)

        def scale_idx(ib, ig, ik, lens, bt):
            blk = _clamp_blk(ik, lens[ib], block_k, window, has_new)
            return (bt[ib, blk], ig, 0, 0)

        def row_idx(ib, ig, ik, lens, bt):
            return (ib, ig, 0, 0)
    else:
        def kv_idx(ib, ig, ik, lens):
            return (
                ib, ig, _clamp_blk(ik, lens[ib], block_k, window, has_new), 0
            )

        def scale_idx(ib, ig, ik, lens):
            return (
                ib, ig, 0, _clamp_blk(ik, lens[ib], block_k, window, has_new)
            )

        def row_idx(ib, ig, ik, lens):
            return (ib, ig, 0, 0)

    kv_block_shape = (1, 1, block_k, hd)
    in_specs = [
        pl.BlockSpec((1, 1, n_rep, hd), row_idx),
        pl.BlockSpec(kv_block_shape, kv_idx),
        pl.BlockSpec(kv_block_shape, kv_idx),
    ]
    inputs = [lengths.astype(jnp.int32)]
    if paged:
        inputs.append(block_table.astype(jnp.int32))
    inputs += [q.reshape(b, n_kv, n_rep, hd), k_cache, v_cache]
    if quant:
        sspec = pl.BlockSpec((1, 1, 8, block_k), scale_idx)
        in_specs += [sspec, sspec]
        inputs += [k_scale, v_scale]
    if has_new:
        # [b, n_kv, hd] → sublane-replicated [b, n_kv, 8, hd] f32 (the
        # finish-step merge runs in f32; mixed-dtype broadcasts fail
        # Mosaic verification) so the block tiles VMEM (a [1, hd] can't).
        rep8 = lambda t: jnp.broadcast_to(  # noqa: E731
            t[:, :, None, :], (b, n_kv, 8, hd)
        ).astype(jnp.float32)
        new_spec = pl.BlockSpec((1, 1, 8, hd), row_idx)
        in_specs += [new_spec, new_spec]
        inputs += [rep8(k_new), rep8(v_new)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 if paged else 1,
        grid=(b, n_kv, n_grid_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, n_rep, hd), row_idx),
        scratch_shapes=[
            pltpu.VMEM((n_rep, hd), jnp.float32),
            pltpu.VMEM((n_rep, 128), jnp.float32),
            pltpu.VMEM((n_rep, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, block_k=block_k, quant=quant,
            has_new=has_new, paged=paged, window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_kv, n_rep, hd), q.dtype),
        interpret=interpret,
    )(*inputs)

    return out.reshape(b, n_heads, hd)
