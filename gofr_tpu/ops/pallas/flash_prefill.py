"""Flash chunk-prefill kernel (pallas TPU): a chunk of c query tokens per
row attends to its slot's KV-cache prefix IN PLACE.

This is the kernel behind chunked prefill (VERDICT r1 weak #9: a long
prompt's prefill must not stall every active decode stream): the engine
splits prompts into fixed-size chunks and interleaves one chunk step
between decode windows. Because the chunk shape is static, serving needs
exactly ONE prefill compile — no bucket ladder — and arbitrary prompt
lengths are handled by the loop count, not the program.

Contract (heads-major cache, ``ops/kv_cache.py``): the chunk's K/V must
already be written into the cache at positions ``starts[p] ..
starts[p]+lens[p]-1`` before the call. Queries are grouped kv-head-major
and token-major within the group: row ``r`` of the ``[c*rep, hd]`` q block
is token ``r // rep``, query-head ``(r % rep)`` of that kv head — so one
MXU matmul per (row-batch × kv block) serves all rep query heads of a kv
head, and the causal mask is computable from the row index alone.

Per-row scalars (slots, starts, lens) ride in SMEM via scalar prefetch;
kv blocks beyond ``starts[p]+lens[p]`` are skipped (clamped index maps →
the pipeline elides the DMA), so cost scales with the true context, not
``max_len``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _clamp_blk(ik, ctx_len, block_k, start=None, window=0):
    """kv block index clamped to the row's VISIBLE range. Windowed: the
    loosest lower bound over the chunk is the FIRST token's (global pos
    ``start``), so blocks wholly below ``start - window + 1`` re-fetch a
    visible block (DMA elided); exact per-token masking happens in the
    body."""
    hi = jnp.maximum(0, (ctx_len - 1) // block_k)
    if window:
        lo = jnp.maximum(0, start - window + 1) // block_k
        return jnp.clip(ik, jnp.minimum(lo, hi), hi)
    return jnp.minimum(ik, hi)


def _kernel(*refs, scale, rep, block_k, quant, paged, window):
    """Grid: (P, n_kv, kv_blocks); kv innermost (scratch carries state).

    quant (static): int8 cache mode — k/v scale refs follow v_ref
    ([8, block_k] sublane-replicated); see ``flash_decode._kernel``.
    paged (static): a 4th prefetched scalar (the block table) follows
    lens; it acts only through the index_maps — the body is unchanged.
    """
    refs = list(refs)
    slot_ref, start_ref, len_ref = refs[:3]
    refs = refs[3:]
    if paged:
        refs.pop(0)  # block table: consumed by the index_maps only
    q_ref, k_ref, v_ref = refs[:3]
    rest = refs[3:]
    if quant:
        k_s_ref, v_s_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    ip = pl.program_id(0)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    start = start_ref[ip]
    clen = len_ref[ip]
    ctx_len = start + clen  # keys visible to the chunk's LAST token

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    last_vis = jnp.clip((ctx_len - 1) // block_k, 0, n_k - 1)
    visible = ik <= last_vis
    if window:
        # Loosest chunk-wide lower bound (first token's window edge);
        # per-token exactness is in the mask below.
        lo_pos = jnp.maximum(0, start - window + 1)
        visible &= ik * block_k + block_k > lo_pos

    @pl.when(visible)
    def _body():
        q = q_ref[0, 0]  # [c*rep, hd]
        k = k_ref[0, 0]  # [block_k, hd]
        v = v_ref[0, 0]
        rows = q.shape[0]
        if quant:
            k = k.astype(q.dtype)
            v = v.astype(jnp.bfloat16)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [c*rep, block_k]
        if quant:
            s = s * k_s_ref[0, 0][0:1, :]

        row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
        t = row // rep  # chunk-token index of each q row
        cols = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_k), 1
        )
        # Causal vs the GLOBAL position start+t; rows past the row's own
        # chunk length are padding queries (fully masked → guarded 0 out).
        mask = jnp.logical_and(cols <= start + t, t < clen)
        if window:
            # Sliding window: keys must sit in (q_pos - window, q_pos].
            mask = jnp.logical_and(mask, cols > start + t - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new[:, :1]), 0.0)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        if quant:
            p = p * v_s_ref[0, 0][0:1, :]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv

    @pl.when(ik == last_vis)
    def _finish():
        l = l_ref[:, :1]
        out = jnp.where(l > 0.0, acc_ref[:] / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "window", "interpret")
)
def flash_cache_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    slots: jnp.ndarray,
    starts: jnp.ndarray,
    lens: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    block_table: jnp.ndarray | None = None,
    scale: float | None = None,
    block_k: int = 256,
    window: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Chunk attention against the slot cache.

    window (static): sliding-window attention — each query attends only
    keys in ``(start+t - window, start+t]``; 0 = full. Masked in-kernel;
    blocks wholly below the chunk's loosest window edge skip their body
    and their DMA.

    q: [P, c, n_heads, hd] — chunk queries (RoPE'd at positions
    starts[p]+t); k_cache, v_cache: [S, n_kv, max_len, hd] with the chunk's
    K/V already written; slots/starts/lens: [P] int32; k_scale/v_scale:
    int8-cache scales [S, n_kv, 8, max_len]. Rows with ``t >= lens[p]``
    return 0. block_table ([S, max_blocks] int32, paged mode): the caches
    are then a POOL [n_blocks, n_kv, block, hd] (scales
    [n_blocks, n_kv, 8, block]); logical kv block ``ik`` of row ``p``
    resolves to pool block ``block_table[slots[p], ik]`` inside the
    BlockSpec index_maps — no per-chunk gather of the whole view.
    Returns [P, c, n_heads, hd].
    """
    P, c, n_heads, hd = q.shape
    paged = block_table is not None
    n_kv = k_cache.shape[1]
    rep = n_heads // n_kv
    quant = k_scale is not None
    if scale is None:
        scale = hd**-0.5
    if paged:
        block_k = k_cache.shape[2]  # pool block size
        n_grid_blocks = block_table.shape[1]
    else:
        max_len = k_cache.shape[2]
        block_k = min(block_k, max_len)
        if max_len % block_k:
            # Persistent cache can't be padded per call; shrink to a
            # divisor.
            block_k = next(
                b for b in (128, 64, 32, 16, 8, 1) if max_len % b == 0
            )
        n_grid_blocks = max_len // block_k

    # [P, c, KV, rep, hd] → [P, KV, c*rep, hd], row = t*rep + head.
    qg = q.reshape(P, c, n_kv, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        P, n_kv, c * rep, hd
    )

    if paged:
        def kv_idx(ip, ig, ik, slots, starts, lens, bt, bk=block_k):
            blk = _clamp_blk(
                ik, starts[ip] + lens[ip], bk, starts[ip], window
            )
            return (bt[slots[ip], blk], ig, 0, 0)

        # Paged scale planes index exactly like K/V (pool block, head).
        scale_idx = kv_idx

        def row_idx(ip, ig, ik, slots, starts, lens, bt):
            return (ip, ig, 0, 0)
    else:
        def kv_idx(ip, ig, ik, slots, starts, lens, bk=block_k):
            blk = _clamp_blk(
                ik, starts[ip] + lens[ip], bk, starts[ip], window
            )
            return (slots[ip], ig, blk, 0)

        def scale_idx(ip, ig, ik, slots, starts, lens, bk=block_k):
            blk = _clamp_blk(
                ik, starts[ip] + lens[ip], bk, starts[ip], window
            )
            return (slots[ip], ig, 0, blk)

        def row_idx(ip, ig, ik, slots, starts, lens):
            return (ip, ig, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, c * rep, hd), row_idx),
        pl.BlockSpec((1, 1, block_k, hd), kv_idx),
        pl.BlockSpec((1, 1, block_k, hd), kv_idx),
    ]
    inputs = [
        slots.astype(jnp.int32), starts.astype(jnp.int32),
        lens.astype(jnp.int32),
    ]
    if paged:
        inputs.append(block_table.astype(jnp.int32))
    inputs += [qg, k_cache, v_cache]
    if quant:
        scale_spec = pl.BlockSpec((1, 1, 8, block_k), scale_idx)
        in_specs += [scale_spec, scale_spec]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 if paged else 3,
        grid=(P, n_kv, n_grid_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, c * rep, hd), row_idx),
        scratch_shapes=[
            pltpu.VMEM((c * rep, hd), jnp.float32),
            pltpu.VMEM((c * rep, 128), jnp.float32),
            pltpu.VMEM((c * rep, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, rep=rep, block_k=block_k, quant=quant,
            paged=paged, window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, n_kv, c * rep, hd), q.dtype),
        interpret=interpret,
    )(*inputs)
    # [P, KV, c*rep, hd] → [P, c, H, hd]
    return out.reshape(P, n_kv, c, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        P, c, n_heads, hd
    )
