"""Flash-attention prefill kernel (pallas TPU).

Online-softmax attention with the kv axis as the innermost (sequential)
grid dimension: running max / denominator / accumulator live in VMEM
scratch and carry across kv iterations, so attention memory is O(block_q ×
head_dim) instead of O(s²). Causal blocks above the diagonal are skipped
entirely with ``pl.when`` (no DMA is wasted on them because their loads are
predicated out with the compute).

Layouts are chosen for the MXU: per grid step the kernel does two
``[block_q, hd] × [hd, block_k]``-shaped matmuls in bf16 with f32
accumulation. GQA is expressed in the BlockSpec index maps (q-head ih reads
kv-head ih // n_rep), not by materialising repeated K/V.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(*refs, scale, causal, s_q, s_kv, block_q, block_k, offset,
            has_lengths, window):
    """Grid: (b, n_heads, q_blocks, kv_blocks); kv innermost.

    With ``has_lengths`` a per-batch valid-length vector rides in SMEM
    (scalar prefetch): keys at ``col >= lengths[b]`` are masked AND kv
    blocks wholly beyond the length are skipped — compute and DMA both
    scale with the true prompt length, not the padding bucket (serving
    prefill's case; VERDICT r1 weak #3).
    """
    if has_lengths:
        len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    ib = pl.program_id(0)
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Rows/cols in global (unpadded) coordinates. ``offset = s_kv - s_q``
    # aligns the causal diagonal when the query is a suffix of the keys.
    row0 = iq * block_q
    col0 = ik * block_k
    length = len_ref[ib] if has_lengths else s_kv
    # Last kv block this q block attends to (causal ∧ within-length); all
    # blocks when neither constraint applies. Clamped to 0 so a q block
    # with NO visible keys still runs block 0 — the in-kernel mask zeroes
    # it and _finish emits the guarded 0 rows instead of uninitialised
    # memory.
    if causal:
        last_vis = jnp.clip(
            (row0 + block_q - 1 + offset) // block_k, 0, n_k - 1
        )
    else:
        last_vis = n_k - 1
    if has_lengths:
        last_vis = jnp.clip(
            jnp.minimum(last_vis, (length - 1) // block_k), 0, n_k - 1
        )
    visible = ik <= last_vis
    if window:
        # Sliding window (causal-only): the q block's FIRST row bounds
        # the loosest visible key; blocks wholly below it are skipped
        # (their loads are predicated out with the compute, like the
        # above-diagonal causal blocks).
        lo_pos = jnp.maximum(0, row0 + offset - window + 1)
        visible &= col0 + block_k > lo_pos

    @pl.when(visible)
    def _body():
        q = q_ref[0, 0]  # [block_q, hd]
        k = k_ref[0, 0]  # [block_k, hd]
        v = v_ref[0, 0]  # [block_k, hd]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]

        rows = row0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = col0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = cols < (length if has_lengths else s_kv)  # invalid keys
        if causal:
            mask = jnp.logical_and(mask, cols <= rows + offset)
        if window:
            mask = jnp.logical_and(mask, cols > rows + offset - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [block_q, 128] (value replicated over lanes)
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [block_q, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 128]
        # Explicit mask: a row whose whole block is masked has m_new =
        # NEG_INF and exp(s - m_new) would be exp(0) = 1, not 0.
        p = jnp.where(
            mask, jnp.exp(s - m_new[:, :1]), 0.0
        )  # [block_q, block_k] f32
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, hd]
        acc_ref[:] = acc_ref[:] * corr[:, :1] + pv

    @pl.when(ik == last_vis)
    def _finish():
        l = l_ref[:, :1]
        # Fully-masked rows (query padding) would divide by zero; emit 0.
        out = jnp.where(l > 0.0, acc_ref[:] / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad)


def _clamp_blk(ik, length, block_k):
    """kv block index clamped to the batch row's last valid block — grid
    steps beyond it re-"fetch" the same block, which the pallas pipeline
    elides (same index → no new DMA)."""
    return jnp.minimum(ik, jnp.maximum(0, (length - 1) // block_k))


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "scale", "block_q", "block_k", "window", "interpret"
    ),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: jnp.ndarray | None = None,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
    window: int = 0,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention. Same contract as ``ops.attention.attention``:

    q: [b, s_q, n_heads, hd]; k, v: [b, s_kv, n_kv_heads, hd];
    causal offset so the last query row attends to all keys when s_kv > s_q.
    lengths: optional [b] int32 valid key-prefix lengths (right-padded
    batches — the serving-prefill case): keys beyond a row's length are
    masked and their kv blocks skipped.
    window (static, causal-only): sliding-window attention — each query
    attends keys in ``(q_pos - window, q_pos]``; 0 = full. Masked
    in-kernel; kv blocks wholly below a q block's window edge are
    skipped like above-diagonal causal blocks. Returns
    [b, s_q, n_heads, hd].
    """
    if window and not causal:
        raise ValueError("window requires causal attention")
    b, s_q, n_heads, hd = q.shape
    s_kv, n_kv = k.shape[1], k.shape[2]
    n_rep = n_heads // n_kv
    if scale is None:
        scale = hd**-0.5

    block_q = min(block_q, max(s_q, 16))
    block_k = min(block_k, max(s_kv, 16))

    # [b, h, s, d] layout: heads as a grid dimension, rows contiguous.
    qt = _pad_to(jnp.swapaxes(q, 1, 2), 2, block_q)
    kt = _pad_to(jnp.swapaxes(k, 1, 2), 2, block_k)
    vt = _pad_to(jnp.swapaxes(v, 1, 2), 2, block_k)
    sq_p, sk_p = qt.shape[2], kt.shape[2]

    grid = (b, n_heads, sq_p // block_q, sk_p // block_k)
    kernel = functools.partial(
        _kernel,
        scale=scale, causal=causal, s_q=s_q, s_kv=s_kv,
        block_q=block_q, block_k=block_k, offset=s_kv - s_q,
        has_lengths=lengths is not None, window=window,
    )
    out_shape = jax.ShapeDtypeStruct((b, n_heads, sq_p, hd), q.dtype)
    scratch_shapes = [
        pltpu.VMEM((block_q, hd), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
    ]
    if lengths is None:
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, hd),
                    lambda ib, ih, iq, ik: (ib, ih, iq, 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda ib, ih, iq, ik, n_rep=n_rep: (ib, ih // n_rep, ik, 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda ib, ih, iq, ik, n_rep=n_rep: (ib, ih // n_rep, ik, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, hd),
                lambda ib, ih, iq, ik: (ib, ih, iq, 0),
            ),
            out_shape=out_shape,
            scratch_shapes=scratch_shapes,
            interpret=interpret,
        )(qt, kt, vt)
    else:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, hd),
                    lambda ib, ih, iq, ik, lens: (ib, ih, iq, 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda ib, ih, iq, ik, lens, n_rep=n_rep, bk=block_k: (
                        ib, ih // n_rep, _clamp_blk(ik, lens[ib], bk), 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_k, hd),
                    lambda ib, ih, iq, ik, lens, n_rep=n_rep, bk=block_k: (
                        ib, ih // n_rep, _clamp_blk(ik, lens[ib], bk), 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, hd),
                lambda ib, ih, iq, ik, lens: (ib, ih, iq, 0),
            ),
            scratch_shapes=scratch_shapes,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(lengths.astype(jnp.int32), qt, kt, vt)

    return jnp.swapaxes(out[:, :, :s_q], 1, 2)
