"""gofr_tpu — a TPU-native application framework.

A brand-new framework with the capability surface of GoFr (an opinionated Go
microservice framework; see SURVEY.md for the structural analysis of the
reference at /root/reference) plus a first-class TPU inference stack that the
reference never had: JAX/XLA models, GSPMD sharding over device meshes,
dynamic-batching serving, and pallas TPU kernels.

Public surface (mirrors the reference's ``pkg/gofr`` top level,
reference ``gofr.go:35-52``):

    from gofr_tpu import App

    app = App()

    @app.get("/hello")
    def hello(ctx):
        return f"Hello {ctx.param('name') or 'World'}!"

    app.run()
"""

from gofr_tpu.version import FRAMEWORK_VERSION

__version__ = FRAMEWORK_VERSION

# Lazy imports keep `import gofr_tpu` cheap (no jax import until the TPU
# surface is touched) while still exposing the GoFr-shaped top level.
_LAZY = {
    "App": ("gofr_tpu.app", "App"),
    "new_cmd": ("gofr_tpu.app", "new_cmd"),
    "Context": ("gofr_tpu.context", "Context"),
    "Migrate": ("gofr_tpu.migration", "Migrate"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'gofr_tpu' has no attribute {name!r}")


__all__ = ["App", "new_cmd", "Context", "Migrate", "FRAMEWORK_VERSION"]
