"""Migration runner.

Behavioral parity with the reference (``migration/migration.go:12-126``):

* migrations are a ``{version: Migrate(up=fn)}`` map; keys validated (>0) and
  run in sorted order (``migration.go:19-26``);
* applied versions are tracked in a ``gofr_migrations`` SQL table
  (``migration/sql.go:13-20``) and/or Redis hash (``migration/redis.go:70-123``),
  whichever datasources exist — the chain-of-responsibility composition of
  ``migration.go:98-126``;
* each migration runs inside a SQL transaction; on failure it rolls back and
  the run stops (``migration.go:63-77``);
* migrations also get pub/sub topic create/delete ops
  (``migration/pubsub.go:5-24``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Callable


@dataclass
class Migrate:
    """One migration; ``up`` receives the datasource bundle
    (reference ``migration/migration.go:12-16``)."""

    up: Callable[["MigrationDatasources"], None]


class MigrationDatasources:
    """What a migration sees: SQL (tx-scoped), Redis, and pub/sub topic admin
    (reference ``migration/datasource.go:12-60``)."""

    def __init__(self, container, sql_tx=None) -> None:
        self._container = container
        self.sql = sql_tx if sql_tx is not None else container.sql
        self.redis = container.redis
        self.pubsub = container.pubsub
        self.logger = container.logger

    def create_topic(self, name: str) -> None:
        if self.pubsub is not None:
            self.pubsub.create_topic(name)

    def delete_topic(self, name: str) -> None:
        if self.pubsub is not None:
            self.pubsub.delete_topic(name)


_SQL_TABLE_DDL = (
    "CREATE TABLE IF NOT EXISTS gofr_migrations ("
    "version INTEGER PRIMARY KEY, method TEXT, start_time TEXT, duration_ms REAL)"
)
_REDIS_HASH = "gofr_migrations"


def _last_migration(container) -> int:
    """Max applied version across trackers (reference ``migration.go:45``)."""
    last = 0
    if container.sql is not None:
        row = container.sql.query_row("SELECT MAX(version) AS v FROM gofr_migrations")
        if row and row.get("v") is not None:
            last = max(last, int(row["v"]))
    if container.redis is not None:
        data = container.redis.hgetall(_REDIS_HASH)
        last = max(last, max((int(k) for k in data), default=0))
    return last


def run(migrations: dict[int, Migrate], container) -> None:
    """Execute pending migrations (reference ``migration/migration.go:18-79``)."""
    logger = container.logger
    if not migrations:
        logger.warn("no migrations to run")
        return
    for key, m in migrations.items():
        if not isinstance(key, int) or isinstance(key, bool) or key <= 0:
            raise ValueError(f"migration version must be a positive int, got {key!r}")
        if not isinstance(m, Migrate) or not callable(m.up):
            raise ValueError(f"migration {key} must be Migrate(up=callable)")

    if container.sql is None and container.redis is None and container.pubsub is None:
        logger.warn("no datasources available for migrations; skipping")
        return

    if container.sql is not None:
        container.sql.exec(_SQL_TABLE_DDL)

    last = _last_migration(container)

    for version in sorted(migrations):
        if version <= last:
            continue
        start = time.time()
        tx = container.sql.begin() if container.sql is not None else None
        ds = MigrationDatasources(container, sql_tx=tx)
        try:
            migrations[version].up(ds)
        except Exception as exc:
            if tx is not None:
                tx.rollback()
            logger.errorf("migration %d failed: %s", version, exc)
            raise
        duration_ms = (time.time() - start) * 1e3
        started_at = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(start))
        if tx is not None:
            from gofr_tpu.datasource.sql.query_builder import insert_query

            # Dialect-aware bindvars: postgres needs $n, mysql/sqlite ?.
            tx.exec(
                insert_query(
                    container.sql.dialect(), "gofr_migrations",
                    ["version", "method", "start_time", "duration_ms"],
                ),
                version,
                "UP",
                started_at,
                duration_ms,
            )
            tx.commit()
        if container.redis is not None:
            container.redis.hset(
                _REDIS_HASH,
                str(version),
                json.dumps(
                    {"method": "UP", "startTime": started_at, "duration": duration_ms}
                ),
            )
        logger.infof("migration %d ran successfully in %.1fms", version, duration_ms)
