"""Versioned data migrations (reference: ``pkg/gofr/migration``)."""

from gofr_tpu.migration.migration import Migrate, MigrationDatasources, run

__all__ = ["Migrate", "MigrationDatasources", "run"]
