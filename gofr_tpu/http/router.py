"""Router with path parameters and middleware chain.

The role gorilla-mux + the default middleware install play in the reference
(``http/router.go:21-49``): method+path routing with ``{param}`` segments,
route-template capture for metrics, 405 detection, and a middleware chain
applied outermost-first (Tracer → Logging → CORS → Metrics by default,
installed by the App).

Middleware here is ``mw(next) -> handler`` over async
``handler(RawRequest) -> Response`` — the direct analog of the reference's
``func(http.Handler) http.Handler`` (``http/router.go:18``).
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional

from gofr_tpu.http.proto import RawRequest, Response

Handler = Callable[[RawRequest], Awaitable[Response]]
Middleware = Callable[[Handler], Handler]


class _Route:
    __slots__ = ("method", "segments", "handler", "template")

    def __init__(self, method: str, template: str, handler: Handler) -> None:
        self.method = method.upper()
        self.template = template
        self.segments = [s for s in template.strip("/").split("/")] if template.strip("/") else []

    def match(self, path_segments: list[str]) -> Optional[dict[str, str]]:
        if len(self.segments) != len(path_segments):
            # Trailing wildcard `{*}`-style catch-all is not used; exact arity.
            return None
        params: dict[str, str] = {}
        for pat, actual in zip(self.segments, path_segments):
            if pat.startswith("{") and pat.endswith("}"):
                params[pat[1:-1]] = actual
            elif pat != actual:
                return None
        return params


class Router:
    def __init__(self, logger=None) -> None:
        self._routes: list[_Route] = []
        self._middlewares: list[Middleware] = []
        self._not_found: Optional[Handler] = None
        self._logger = logger

    # -- registration (reference http/router.go:36-49) -------------------

    def add(self, method: str, template: str, handler: Handler) -> None:
        route = _Route(method, template, handler)
        route.handler = handler
        self._routes.append(route)

    def use_middleware(self, *mws: Middleware) -> None:
        self._middlewares.extend(mws)

    def set_not_found(self, handler: Handler) -> None:
        self._not_found = handler

    def routes(self) -> list[tuple[str, str]]:
        return [(r.method, r.template) for r in self._routes]

    # -- dispatch --------------------------------------------------------

    async def __call__(self, raw: RawRequest) -> Response:
        handler = self._resolve(raw)
        # Middlewares wrap the resolved handler, outermost = first installed
        # (reverse-registration order like the reference chain, SURVEY §3.2).
        for mw in reversed(self._middlewares):
            handler = mw(handler)
        return await handler(raw)

    def _resolve(self, raw: RawRequest) -> Handler:
        from urllib.parse import urlsplit, unquote

        path = unquote(urlsplit(raw.target).path) or "/"
        path_segments = [s for s in path.strip("/").split("/")] if path.strip("/") else []

        method_mismatch = False
        for route in self._routes:
            params = route.match(path_segments)
            if params is None:
                continue
            if route.method != raw.method and not (
                raw.method == "HEAD" and route.method == "GET"
            ):
                method_mismatch = True
                continue
            raw.route_template = route.template
            raw.path_params = params
            return route.handler

        if method_mismatch:
            return _status_handler(405)
        if self._not_found is not None:
            raw.route_template = "/"
            return self._not_found
        return _status_handler(404)


def _status_handler(status: int) -> Handler:
    async def handler(_: RawRequest) -> Response:
        import json

        msg = "Method Not Allowed" if status == 405 else "route not registered"
        return Response(
            status=status,
            headers={"Content-Type": "application/json"},
            body=json.dumps({"error": {"message": msg}}).encode(),
        )

    return handler
