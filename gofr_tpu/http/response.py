"""Response passthrough types (reference ``pkg/gofr/http/response``).

Returning these from a handler bypasses the JSON ``{"data": ...}`` envelope:

* :class:`Raw` — serialize the wrapped value as bare JSON
  (reference ``http/response/raw.go:3-5``);
* :class:`File` — raw bytes with a content type
  (reference ``http/response/file.go:3-6``);
* :class:`Redirect` — 302 Location redirect.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class Raw:
    data: Any
    # Override the method-derived success status (e.g. OpenAI-compat
    # POSTs answer 200, not the framework's POST→201 default).
    status: Any = None


@dataclass
class File:
    content: bytes
    content_type: str = "application/octet-stream"


@dataclass
class Redirect:
    url: str
    status: int = 302


@dataclass
class Stream:
    """Chunked streaming response (SSE by default).

    ``chunks``: an async iterator of ``bytes`` (or ``str``, encoded
    utf-8). The server sends ``Transfer-Encoding: chunked`` and writes
    each chunk as it arrives — token streaming over plain HTTP.
    """

    chunks: Any
    content_type: str = "text/event-stream"
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class TypedResponse:
    """Full-control response: data plus extra headers/metadata."""

    data: Any
    headers: dict[str, str] = field(default_factory=dict)
    metadata: dict[str, Any] = field(default_factory=dict)
