"""Wire-level HTTP/1.1 request/response structs and (de)serialization.

This is the layer Go's ``net/http`` provides the reference for free; here it
is implemented natively on asyncio streams: request-line/header parsing with
size limits, Content-Length and chunked bodies, keep-alive accounting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

MAX_REQUEST_LINE = 8192
MAX_HEADER_COUNT = 100
MAX_HEADER_LINE = 8192
MAX_BODY_BYTES = 64 * 1024 * 1024  # matches a generous server default

STATUS_TEXT = {
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    206: "Partial Content", 301: "Moved Permanently", 302: "Found",
    304: "Not Modified", 307: "Temporary Redirect", 308: "Permanent Redirect",
    400: "Bad Request", 401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout", 409: "Conflict",
    411: "Length Required", 413: "Payload Too Large", 415: "Unsupported Media Type",
    422: "Unprocessable Entity", 429: "Too Many Requests",
    500: "Internal Server Error", 501: "Not Implemented", 502: "Bad Gateway",
    503: "Service Unavailable", 504: "Gateway Timeout", 505: "HTTP Version Not Supported",
}


class ProtocolError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status


@dataclass
class RawRequest:
    method: str
    target: str  # path?query as received
    version: str  # "HTTP/1.1"
    headers: dict[str, str]  # keys lower-cased; repeated headers comma-joined
    body: bytes
    peer: Optional[tuple] = None
    # Filled by the router at match time; read by middleware/handlers.
    route_template: str = ""
    path_params: dict = field(default_factory=dict)
    # Cross-middleware request-scoped values (e.g. JWT claims, trace span) —
    # the role context.WithValue plays in the reference middleware.
    ctx_data: dict = field(default_factory=dict)

    @property
    def keep_alive(self) -> bool:
        conn = self.headers.get("connection", "").lower()
        if self.version == "HTTP/1.0":
            return "keep-alive" in conn
        return "close" not in conn


@dataclass
class Response:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # Streaming body (SSE, chunked downloads): an async iterator of bytes.
    # When set, the server sends Transfer-Encoding: chunked and writes
    # chunks as they arrive; ``body`` is ignored.
    body_stream: Optional[Any] = None

    def set_header(self, key: str, value: str) -> None:
        self.headers[key] = value


async def read_request(reader, peer=None, first_line: Optional[bytes] = None) -> Optional[RawRequest]:
    """Parse one request off the stream. Returns None on clean EOF before a
    request line; raises ProtocolError on malformed input. ``first_line``
    lets the server read the request line itself (to detect when a request
    starts arriving) and hand the rest off here."""
    line = first_line if first_line is not None else await reader.readline()
    if not line:
        return None
    if len(line) > MAX_REQUEST_LINE:
        raise ProtocolError(414, "request line too long")
    try:
        method, target, version = line.decode("latin-1").rstrip("\r\n").split(" ", 2)
    except ValueError:
        raise ProtocolError(400, "malformed request line") from None
    if version not in ("HTTP/1.1", "HTTP/1.0"):
        raise ProtocolError(505, "unsupported HTTP version")

    headers: dict[str, str] = {}
    for _ in range(MAX_HEADER_COUNT + 1):
        hline = await reader.readline()
        if len(hline) > MAX_HEADER_LINE:
            raise ProtocolError(431, "header line too long")
        if hline in (b"\r\n", b"\n", b""):
            break
        try:
            key, _, value = hline.decode("latin-1").partition(":")
        except UnicodeDecodeError:
            raise ProtocolError(400, "bad header encoding") from None
        key = key.strip().lower()
        value = value.strip()
        if not key or not _:
            raise ProtocolError(400, "malformed header")
        if key in headers:
            headers[key] += ", " + value
        else:
            headers[key] = value
    else:
        raise ProtocolError(431, "too many headers")

    body = b""
    if headers.get("transfer-encoding", "").lower() == "chunked":
        chunks = []
        total = 0
        while True:
            size_line = await reader.readline()
            try:
                size = int(size_line.strip().split(b";")[0], 16)
            except ValueError:
                raise ProtocolError(400, "bad chunk size") from None
            if size == 0:
                # trailing headers until blank line
                while True:
                    t = await reader.readline()
                    if t in (b"\r\n", b"\n", b""):
                        break
                break
            total += size
            if total > MAX_BODY_BYTES:
                raise ProtocolError(413, "body too large")
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # CRLF
        body = b"".join(chunks)
    elif "content-length" in headers:
        try:
            length = int(headers["content-length"])
        except ValueError:
            raise ProtocolError(400, "bad content-length") from None
        if length < 0:
            raise ProtocolError(400, "bad content-length")
        if length > MAX_BODY_BYTES:
            raise ProtocolError(413, "body too large")
        body = await reader.readexactly(length)

    return RawRequest(
        method=method, target=target, version=version, headers=headers,
        body=body, peer=peer,
    )


def serialize_response(resp: Response, *, head_only: bool = False, keep_alive: bool = True) -> bytes:
    status_text = STATUS_TEXT.get(resp.status, "Unknown")
    headers = dict(resp.headers)
    headers.setdefault("Date", time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime()))
    headers.setdefault("Server", "gofr-tpu")
    streaming = resp.body_stream is not None and not head_only
    if streaming:
        headers["Transfer-Encoding"] = "chunked"
        headers.pop("Content-Length", None)
    elif resp.status not in (204, 304):
        headers["Content-Length"] = str(len(resp.body))
    if not keep_alive:
        headers["Connection"] = "close"
    head = f"HTTP/1.1 {resp.status} {status_text}\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in headers.items()
    ) + "\r\n"
    out = head.encode("latin-1")
    if not head_only and not streaming and resp.status not in (204, 304):
        out += resp.body
    return out


def chunk_encode(data: bytes) -> bytes:
    """One HTTP/1.1 chunked-transfer chunk."""
    return f"{len(data):x}\r\n".encode("latin-1") + data + b"\r\n"


CHUNKED_TERMINATOR = b"0\r\n\r\n"
