"""Framework HTTP Request (reference ``pkg/gofr/http/request.go:28-121``).

Wraps the wire-level :class:`~gofr_tpu.http.proto.RawRequest` with the
``gofr.Request`` capability set: query/path params, JSON + form +
multipart bind into dataclasses or dicts, hostname.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Optional
from urllib.parse import parse_qs, unquote, urlsplit

from gofr_tpu.errors import ErrorInvalidParam
from gofr_tpu.http.proto import RawRequest


@dataclasses.dataclass
class UploadedFile:
    """A bound multipart file part (role of ``file.Zip`` /
    ``*multipart.FileHeader`` in reference ``http/multipartFileBind.go``)."""

    filename: str
    content_type: str
    data: bytes


class Request:
    def __init__(self, raw: RawRequest) -> None:
        self._raw = raw
        split = urlsplit(raw.target)
        self.path = unquote(split.path) or "/"
        self._query = parse_qs(split.query, keep_blank_values=True)

    # -- identity --------------------------------------------------------

    @property
    def method(self) -> str:
        return self._raw.method

    @property
    def raw(self) -> RawRequest:
        return self._raw

    def host_name(self) -> str:
        """Scheme+host like reference ``http/request.go`` ``HostName``."""
        proto = self._raw.headers.get("x-forwarded-proto", "http")
        return f"{proto}://{self._raw.headers.get('host', '')}"

    def header(self, key: str) -> Optional[str]:
        return self._raw.headers.get(key.lower())

    @property
    def headers(self) -> dict[str, str]:
        return dict(self._raw.headers)

    # -- params ----------------------------------------------------------

    def param(self, key: str) -> str:
        """First query-string value for ``key`` ('' when absent)."""
        vals = self._query.get(key)
        return vals[0] if vals else ""

    def params(self, key: str) -> list[str]:
        """All values for ``key``, splitting comma-separated entries
        (reference ``http/request.go`` ``Params``)."""
        out: list[str] = []
        for v in self._query.get(key, []):
            out.extend(x for x in v.split(",") if x != "")
        return out

    def path_param(self, key: str) -> str:
        return self._raw.path_params.get(key, "")

    # -- body / bind -----------------------------------------------------

    @property
    def body(self) -> bytes:
        return self._raw.body

    def json(self) -> Any:
        try:
            return json.loads(self._raw.body or b"null")
        except json.JSONDecodeError as exc:
            raise ErrorInvalidParam(["body"]) from exc

    def bind(self, target: Any) -> Any:
        """Bind the request body into ``target``.

        * JSON bodies bind into a dataclass type/instance or dict
          (reference ``http/request.go`` ``Bind``);
        * ``multipart/form-data`` binds form fields by name and file parts
          as :class:`UploadedFile` (reference ``http/multipartFileBind.go``);
        * ``application/x-www-form-urlencoded`` binds form fields by name.
        """
        ctype = self._raw.headers.get("content-type", "application/json")
        if ctype.startswith("multipart/form-data"):
            fields, files = self._parse_multipart(ctype)
            merged: dict[str, Any] = {**fields, **files}
            return _fill(target, merged)
        if ctype.startswith("application/x-www-form-urlencoded"):
            form = {
                k: v[0]
                for k, v in parse_qs(
                    self._raw.body.decode("utf-8", "replace"), keep_blank_values=True
                ).items()
            }
            return _fill(target, form)
        data = self.json()
        if not isinstance(data, dict):
            raise ErrorInvalidParam(["body"])
        return _fill(target, data)

    def _parse_multipart(self, ctype: str):
        match = re.search(r'boundary="?([^";]+)"?', ctype)
        if not match:
            raise ErrorInvalidParam(["content-type boundary"])
        boundary = b"--" + match.group(1).encode()
        fields: dict[str, str] = {}
        files: dict[str, UploadedFile] = {}
        for part in self._raw.body.split(boundary)[1:]:
            # Strip exactly the delimiter CRLFs, not all leading/trailing
            # newline bytes — file DATA may legitimately end in newlines
            # (e.g. a JSONL upload) and must round-trip byte-exact.
            if part.startswith(b"\r\n"):
                part = part[2:]
            if part.endswith(b"\r\n"):
                part = part[:-2]
            if part.strip(b"\r\n \t") in (b"", b"--"):
                continue
            header_blob, _, content = part.partition(b"\r\n\r\n")
            headers: dict[str, str] = {}
            for line in header_blob.split(b"\r\n"):
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            disp = headers.get("content-disposition", "")
            name_m = re.search(r'name="([^"]*)"', disp)
            if not name_m:
                continue
            name = name_m.group(1)
            file_m = re.search(r'filename="([^"]*)"', disp)
            if file_m:
                files[name] = UploadedFile(
                    filename=file_m.group(1),
                    content_type=headers.get("content-type", "application/octet-stream"),
                    data=content,
                )
            else:
                fields[name] = content.decode("utf-8", "replace")
        return fields, files


def _coerce(value: Any, typ: Any) -> Any:
    # `from __future__ import annotations` stringifies dataclass field types.
    if isinstance(typ, str):
        typ = {"int": int, "float": float, "bool": bool, "str": str}.get(typ, typ)
    try:
        if typ is int and not isinstance(value, bool):
            return int(value)
        if typ is float:
            return float(value)
        if typ is bool and isinstance(value, str):
            return value.lower() in ("true", "1", "yes", "on")
        if typ is str and not isinstance(value, str):
            return str(value)
    except (TypeError, ValueError):
        return value
    return value


def _fill(target: Any, data: dict[str, Any]) -> Any:
    """Populate ``target`` (dict, dataclass type, dataclass instance, or
    plain object) from ``data`` — the reflective walk the reference does in
    ``http/multipartFileBind.go:11-130``."""
    if isinstance(target, dict):
        target.update(data)
        return target
    if isinstance(target, type) and dataclasses.is_dataclass(target):
        kwargs = {}
        for f in dataclasses.fields(target):
            if f.name in data:
                kwargs[f.name] = _coerce(data[f.name], f.type)
        return target(**kwargs)
    if dataclasses.is_dataclass(target):
        for f in dataclasses.fields(target):
            if f.name in data:
                setattr(target, f.name, _coerce(data[f.name], f.type))
        return target
    for key, value in data.items():
        if hasattr(target, key):
            setattr(target, key, value)
    return target
