"""HTTP Responder (reference ``pkg/gofr/http/responder.go:12-80``).

Maps a handler's ``(result, error)`` into the wire response:

* success → ``{"data": <result>}`` JSON envelope;
* error → ``{"error": {"message": ...}}`` with status from the error type
  (``status_code`` attribute honored, reference ``responder.go:53-74``);
* status from method when no error: POST → 201, DELETE → 204 and
  everything else → 200 (reference ``responder.go:27-41``);
* :class:`Raw` / :class:`File` / :class:`Redirect` bypass the envelope
  (reference ``responder.go:24-26`` + ``response`` package).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

import numpy as _np

from gofr_tpu.http.proto import Response
from gofr_tpu.http.response import File, Raw, Redirect, Stream, TypedResponse


def _default(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    if isinstance(obj, _np.ndarray):
        return obj.tolist()
    if isinstance(obj, (_np.integer,)):
        return int(obj)
    if isinstance(obj, (_np.floating,)):
        return float(obj)
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "replace")
    if hasattr(obj, "tolist"):  # jax arrays
        return obj.tolist()
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    return str(obj)


def to_json_bytes(payload: Any) -> bytes:
    return json.dumps(payload, default=_default).encode("utf-8")


class Responder:
    def __init__(self, method: str = "GET") -> None:
        self._method = method

    def respond(self, result: Any, error: Optional[BaseException]) -> Response:
        if error is not None:
            status = self.status_from_error(error)
            headers = {"Content-Type": "application/json"}
            # Errors may carry wire headers (e.g. Retry-After on a shed
            # 429 — errors.ErrorTooManyRequests) so well-behaved clients
            # back off instead of hammering an overloaded engine.
            extra = getattr(error, "headers", None)
            if isinstance(extra, dict):
                headers.update({str(k): str(v) for k, v in extra.items()})
            return Response(
                status=status,
                headers=headers,
                body=to_json_bytes({"error": {"message": str(error) or "unknown error"}}),
            )

        if isinstance(result, Response):  # already wire-level
            return result
        if isinstance(result, Redirect):
            return Response(status=result.status, headers={"Location": result.url})
        if isinstance(result, File):
            return Response(
                status=200,
                headers={"Content-Type": result.content_type},
                body=result.content,
            )
        if isinstance(result, Raw):
            return Response(
                status=result.status or self._success_status(),
                headers={"Content-Type": "application/json"},
                body=to_json_bytes(result.data),
            )
        if isinstance(result, Stream):
            async def _encoded(chunks=result.chunks):
                async for chunk in chunks:
                    yield chunk.encode() if isinstance(chunk, str) else chunk

            return Response(
                status=200,
                headers={
                    "Content-Type": result.content_type,
                    "Cache-Control": "no-cache",
                    **result.headers,
                },
                body_stream=_encoded(),
            )
        if isinstance(result, TypedResponse):
            headers = {"Content-Type": "application/json", **result.headers}
            envelope: dict[str, Any] = {"data": result.data}
            if result.metadata:
                envelope["metadata"] = result.metadata
            return Response(
                status=self._success_status(),
                headers=headers,
                body=to_json_bytes(envelope),
            )

        status = self._success_status()
        body = b"" if status == 204 else to_json_bytes({"data": result})
        return Response(
            status=status, headers={"Content-Type": "application/json"}, body=body
        )

    def _success_status(self) -> int:
        # Reference responder.go:27-41.
        if self._method == "POST":
            return 201
        if self._method == "DELETE":
            return 204
        return 200

    @staticmethod
    def status_from_error(error: BaseException) -> int:
        status = getattr(error, "status_code", None)
        if callable(status):
            status = status()
        if isinstance(status, int):
            return status
        return 500
