"""HTTP transport layer (reference: ``pkg/gofr/http``).

A from-scratch asyncio HTTP/1.1 server (the role net/http + gorilla-mux play
in the reference), the framework ``Request``/``Responder`` implementations,
the router with path parameters and middleware chain, and the default
middleware set (Tracer → Logging → CORS → Metrics,
reference ``http/router.go:23-28``).
"""

from gofr_tpu.http.proto import RawRequest, Response
from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import Responder
from gofr_tpu.http.response import File, Raw, Redirect, Stream
from gofr_tpu.http.router import Router
from gofr_tpu.http.server import HTTPServer

__all__ = [
    "RawRequest",
    "Response",
    "Request",
    "Responder",
    "Raw",
    "File",
    "Redirect",
    "Stream",
    "Router",
    "HTTPServer",
]
