"""Basic auth middleware (reference ``http/middleware/basic_auth.go:18-73``).

Validates ``Authorization: Basic`` against a static user→password map or a
user-supplied validate function. Well-known probe routes are exempt
(reference ``http/middleware/validate.go:5-7``).
"""

from __future__ import annotations

import base64
import json

from gofr_tpu.http.proto import Response

EXEMPT_PREFIXES = ("/.well-known/",)


def _unauthorized(msg: str = "Unauthorized") -> Response:
    return Response(
        status=401,
        headers={"Content-Type": "application/json", "WWW-Authenticate": "Basic"},
        body=json.dumps({"error": {"message": msg}}).encode(),
    )


def basic_auth_middleware(users: dict[str, str] | None = None, validate_func=None, container=None):
    def mw(next_handler):
        async def handler(raw):
            path = raw.target.split("?")[0]
            if any(path.startswith(p) for p in EXEMPT_PREFIXES):
                return await next_handler(raw)
            header = raw.headers.get("authorization", "")
            if not header.startswith("Basic "):
                return _unauthorized()
            try:
                decoded = base64.b64decode(header[6:]).decode("utf-8")
                username, _, password = decoded.partition(":")
            except Exception:
                return _unauthorized("invalid authorization header")
            if validate_func is not None:
                # Reference passes the container to custom validators
                # (gofr.go:316 EnableBasicAuthWithValidator).
                ok = (
                    validate_func(container, username, password)
                    if container is not None
                    else validate_func(username, password)
                )
                if not ok:
                    return _unauthorized()
            elif users is None or users.get(username) != password:
                return _unauthorized()
            raw.ctx_data["auth.user"] = username
            return await next_handler(raw)

        return handler

    return mw
