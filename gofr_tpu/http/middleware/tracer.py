"""Tracer middleware (reference ``http/middleware/tracer.go:15-32``).

Extracts the W3C ``traceparent`` header and opens a span named
``"METHOD /route"`` for the request; the span rides the request's
``ctx_data`` for downstream middleware/handlers.
"""

from __future__ import annotations

from gofr_tpu.tracing import extract_traceparent, get_tracer


def tracer_middleware(tracer=None):
    def mw(next_handler):
        async def handler(raw):
            t = tracer or get_tracer()
            trace_id, parent_id = extract_traceparent(raw.headers)
            span = t.start_span(
                f"{raw.method} {raw.route_template or raw.target}",
                trace_id=trace_id,
                parent_span_id=parent_id,
                attributes={"http.method": raw.method, "http.target": raw.target},
            )
            raw.ctx_data["span"] = span
            try:
                resp = await next_handler(raw)
                span.set_attribute("http.status_code", resp.status)
                if resp.status >= 500:
                    span.set_status("ERROR")
                return resp
            finally:
                span.end()

        return handler

    return mw
