"""API-key auth middleware (reference ``http/middleware/apikey_auth.go:11-57``).

Checks ``X-API-KEY`` against a static key list or a validator function.
"""

from __future__ import annotations

import json

from gofr_tpu.http.proto import Response
from gofr_tpu.http.middleware.basic_auth import EXEMPT_PREFIXES


def apikey_auth_middleware(keys=(), validate_func=None, container=None):
    keyset = set(keys)

    def mw(next_handler):
        async def handler(raw):
            path = raw.target.split("?")[0]
            if any(path.startswith(p) for p in EXEMPT_PREFIXES):
                return await next_handler(raw)
            key = raw.headers.get("x-api-key", "")
            if validate_func is not None:
                ok = (
                    validate_func(container, key)
                    if container is not None
                    else validate_func(key)
                )
            else:
                ok = key in keyset
            if not ok:
                return Response(
                    status=401,
                    headers={"Content-Type": "application/json"},
                    body=json.dumps({"error": {"message": "Unauthorized"}}).encode(),
                )
            return await next_handler(raw)

        return handler

    return mw
