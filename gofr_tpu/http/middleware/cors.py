"""CORS middleware (reference ``http/middleware/cors.go:6-23``).

Wildcard allow headers on every response; OPTIONS preflight short-circuits
with 200.
"""

from __future__ import annotations

from gofr_tpu.http.proto import Response

_CORS_HEADERS = {
    "Access-Control-Allow-Origin": "*",
    "Access-Control-Allow-Methods": "GET, POST, PUT, PATCH, DELETE, OPTIONS",
    "Access-Control-Allow-Headers": "Content-Type, Authorization, X-API-KEY, traceparent",
}


def cors_middleware(overrides: dict | None = None):
    headers = {**_CORS_HEADERS, **(overrides or {})}

    def mw(next_handler):
        async def handler(raw):
            if raw.method == "OPTIONS":
                return Response(status=200, headers=dict(headers))
            resp = await next_handler(raw)
            for k, v in headers.items():
                resp.headers.setdefault(k, v)
            return resp

        return handler

    return mw
