"""OAuth / JWT middleware (reference ``http/middleware/oauth.go:22-194``).

* :class:`JWKSProvider` refreshes a JWKS endpoint on a background daemon
  thread and caches RSA public keys by ``kid``
  (reference ``oauth.go:53-86,94-140``);
* the middleware parses ``Authorization: Bearer`` JWTs (RS256 via the
  ``cryptography`` package, HS256 via hmac for shared-secret setups),
  validates signature + ``exp``, and stashes claims in the request context
  under ``"JWTClaims"`` (reference ``oauth.go:143-194``).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.request

from gofr_tpu.http.proto import Response
from gofr_tpu.http.middleware.basic_auth import EXEMPT_PREFIXES


def _b64url_decode(segment: str) -> bytes:
    pad = "=" * (-len(segment) % 4)
    return base64.urlsafe_b64decode(segment + pad)


def _b64url_to_int(segment: str) -> int:
    return int.from_bytes(_b64url_decode(segment), "big")


class JWKSProvider:
    """kid → RSA public key cache with periodic refresh."""

    def __init__(self, jwks_url: str, refresh_interval_s: float = 300.0, logger=None) -> None:
        self._url = jwks_url
        self._interval = refresh_interval_s
        self._logger = logger
        self._keys: dict[str, object] = {}
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        self.refresh()
        self._thread = threading.Thread(target=self._run, name="jwks-refresh", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.refresh()

    def refresh(self) -> None:
        try:
            with urllib.request.urlopen(self._url, timeout=5) as resp:
                jwks = json.loads(resp.read().decode())
            keys = {}
            for jwk in jwks.get("keys", []):
                if jwk.get("kty") != "RSA":
                    continue
                try:
                    from cryptography.hazmat.primitives.asymmetric.rsa import (
                        RSAPublicNumbers,
                    )

                    pub = RSAPublicNumbers(
                        e=_b64url_to_int(jwk["e"]), n=_b64url_to_int(jwk["n"])
                    ).public_key()
                    keys[jwk.get("kid", "")] = pub
                except Exception:
                    continue
            with self._lock:
                self._keys = keys
        except Exception as exc:
            if self._logger is not None:
                self._logger.debugf("JWKS refresh failed: %s", exc)

    def key(self, kid: str):
        with self._lock:
            return self._keys.get(kid)


def _verify_jwt(token: str, *, jwks: JWKSProvider | None, hs_secret: bytes | None):
    """Returns claims dict or raises ValueError."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
        signature = _b64url_decode(sig_b64)
    except Exception as exc:
        raise ValueError("malformed token") from exc

    alg = header.get("alg")
    signing_input = f"{header_b64}.{payload_b64}".encode()
    if alg == "RS256":
        if jwks is None:
            raise ValueError("no JWKS provider configured")
        key = jwks.key(header.get("kid", ""))
        if key is None:
            raise ValueError("unknown key id")
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        from cryptography.exceptions import InvalidSignature

        try:
            key.verify(signature, signing_input, padding.PKCS1v15(), hashes.SHA256())
        except InvalidSignature as exc:
            raise ValueError("invalid signature") from exc
    elif alg == "HS256":
        if hs_secret is None:
            raise ValueError("no shared secret configured")
        expected = hmac.new(hs_secret, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, signature):
            raise ValueError("invalid signature")
    else:
        raise ValueError(f"unsupported alg {alg}")

    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp):
        raise ValueError("token expired")
    return payload


def oauth_middleware(jwks: JWKSProvider | None = None, hs_secret: bytes | None = None):
    def mw(next_handler):
        async def handler(raw):
            path = raw.target.split("?")[0]
            if any(path.startswith(p) for p in EXEMPT_PREFIXES):
                return await next_handler(raw)
            header = raw.headers.get("authorization", "")
            if not header.startswith("Bearer "):
                return _unauthorized("authorization header missing")
            try:
                claims = _verify_jwt(header[7:], jwks=jwks, hs_secret=hs_secret)
            except ValueError as exc:
                return _unauthorized(str(exc))
            # Claims key matches the reference's JWTClaim context key
            # (oauth.go:22-24) so handlers find them under one name.
            raw.ctx_data["JWTClaims"] = claims
            return await next_handler(raw)

        return handler

    return mw


def _unauthorized(msg: str) -> Response:
    return Response(
        status=401,
        headers={"Content-Type": "application/json"},
        body=json.dumps({"error": {"message": msg}}).encode(),
    )
