"""Request logging + panic recovery middleware
(reference ``http/middleware/logger.go:16-146``).

* logs a structured ``RequestLog`` (trace id, ip, method, uri, status,
  response time) after each request;
* surfaces the trace id as ``X-Correlation-ID`` (reference ``logger.go:80``);
* recovers handler/middleware exceptions into a 500 JSON envelope with the
  stack logged (reference ``logger.go:121-146``).
"""

from __future__ import annotations

import json
import time
import traceback
from dataclasses import dataclass

from gofr_tpu.http.proto import Response


@dataclass
class RequestLog:
    trace_id: str
    span_id: str
    start_time: str
    response_time_us: int
    method: str
    ip: str
    uri: str
    response: int

    def to_log_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start_time": self.start_time,
            "response_time": self.response_time_us,
            "method": self.method,
            "ip": self.ip,
            "uri": self.uri,
            "response": self.response,
        }

    def pretty_print(self, fp) -> None:
        # Colorized terminal line (reference logger.go:102-115).
        color = 32 if self.response < 400 else (33 if self.response < 500 else 31)
        fp.write(
            f"\x1b[{color}m{self.response}\x1b[0m "
            f"{self.response_time_us:>8}µs {self.method:>6} {self.uri} "
            f"(trace {self.trace_id})\n"
        )


def logging_middleware(logger):
    def mw(next_handler):
        async def handler(raw):
            start = time.time()
            span = raw.ctx_data.get("span")
            trace_id = span.trace_id if span is not None else ""
            span_id = span.span_id if span is not None else ""
            try:
                resp = await next_handler(raw)
            except Exception:
                logger.errorf(
                    "panic recovered in handler %s %s:\n%s",
                    raw.method,
                    raw.target,
                    traceback.format_exc(),
                )
                resp = Response(
                    status=500,
                    headers={"Content-Type": "application/json"},
                    body=json.dumps(
                        {"error": {"message": "some unexpected error has occurred"}}
                    ).encode(),
                )
            if trace_id:
                resp.set_header("X-Correlation-ID", trace_id)
            elapsed_us = int((time.time() - start) * 1e6)
            ip = raw.headers.get("x-forwarded-for")
            if not ip and raw.peer:
                ip = f"{raw.peer[0]}:{raw.peer[1]}"
            log = RequestLog(
                trace_id=trace_id,
                span_id=span_id,
                start_time=time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(start)),
                response_time_us=elapsed_us,
                method=raw.method,
                ip=ip or "",
                uri=raw.target,
                response=resp.status,
            )
            if resp.status >= 500:
                logger.error(log)
            else:
                logger.info(log)
            return resp

        return handler

    return mw
