"""Metrics middleware (reference ``http/middleware/metrics.go:21-44``).

Records the ``app_http_response`` histogram labeled by route template
(not raw path — bounded cardinality), method, and status.
"""

from __future__ import annotations

import time


def metrics_middleware(metrics):
    def mw(next_handler):
        async def handler(raw):
            start = time.time()
            resp = await next_handler(raw)
            metrics.record_histogram(
                "app_http_response",
                time.time() - start,
                "path",
                raw.route_template or raw.target.split("?")[0],
                "method",
                raw.method,
                "status",
                str(resp.status),
            )
            return resp

        return handler

    return mw
