"""Server middleware (reference: ``pkg/gofr/http/middleware``).

All middleware are ``mw(next) -> handler`` over async
``handler(RawRequest) -> Response`` — the analog of the reference's
``func(http.Handler) http.Handler``. The default chain is
Tracer → Logging → CORS → Metrics (reference ``http/router.go:23-28``).
"""

from gofr_tpu.http.middleware.tracer import tracer_middleware
from gofr_tpu.http.middleware.logging_mw import logging_middleware
from gofr_tpu.http.middleware.metrics_mw import metrics_middleware
from gofr_tpu.http.middleware.cors import cors_middleware
from gofr_tpu.http.middleware.basic_auth import basic_auth_middleware
from gofr_tpu.http.middleware.apikey_auth import apikey_auth_middleware
from gofr_tpu.http.middleware.oauth import oauth_middleware, JWKSProvider

__all__ = [
    "tracer_middleware",
    "logging_middleware",
    "metrics_middleware",
    "cors_middleware",
    "basic_auth_middleware",
    "apikey_auth_middleware",
    "oauth_middleware",
    "JWKSProvider",
]
