"""Asyncio HTTP/1.1 server.

Plays the role of ``net/http.Server`` in the reference (``httpServer.go:12-36``)
but adds what the reference lacks: graceful shutdown with connection draining
(the reference's ``Run()`` blocks forever, ``gofr.go:169`` — SURVEY §3.1 flags
this as a gap the build must close, since queued batched inference makes
drain-on-shutdown mandatory).

* per-connection read deadline mirroring the reference's 5s
  ``ReadHeaderTimeout`` (``httpServer.go:27``);
* keep-alive with pipelined sequential requests;
* the handler is ``async fn(RawRequest) -> Response``;
* request lifecycle: an ``X-Request-Timeout`` header (seconds) becomes a
  :class:`Deadline` and every request carries a :class:`CancelToken` in
  ``ctx_data`` — the token trips when the connection dies mid-request,
  so a generation handler's engine work is retired instead of decoding
  for a client that is gone (docs/advanced-guide/resilience.md).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

from gofr_tpu.http.proto import (
    CHUNKED_TERMINATOR,
    ProtocolError,
    RawRequest,
    Response,
    chunk_encode,
    read_request,
    serialize_response,
)
from gofr_tpu.serving.lifecycle import CancelToken, Deadline

Handler = Callable[[RawRequest], Awaitable[Response]]

READ_HEADER_TIMEOUT_S = 5.0  # reference httpServer.go:27
KEEPALIVE_IDLE_TIMEOUT_S = 75.0
# The reference sets only ReadHeaderTimeout; bodies may stream for as long
# as they need. Bound them generously instead of inheriting the 5s header
# budget (which would reset slow uploads mid-stream with no response).
BODY_READ_TIMEOUT_S = 300.0
# Per-request deadline header: seconds the client is willing to wait.
# Parsed here (the transport edge) so every surface — framework routes,
# OpenAI endpoints, ctx.infer — sees the same Deadline on ctx_data.
REQUEST_TIMEOUT_HEADER = "x-request-timeout"


class HTTPServer:
    def __init__(
        self,
        handler: Handler,
        port: int,
        host: str = "0.0.0.0",
        logger=None,
    ) -> None:
        self._handler = handler
        self.host = host
        self.port = port
        self._logger = logger
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[asyncio.StreamWriter] = set()
        self._inflight: set[asyncio.StreamWriter] = set()  # mid-request conns
        self.drain_timeout_s = 30.0

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_connection, host=self.host, port=self.port
        )
        # Port 0 → pick the bound port back up for tests.
        sock = self._server.sockets[0]
        self.port = sock.getsockname()[1]
        if self._logger is not None:
            self._logger.infof("HTTP server started on :%d", self.port)

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._server.serve_forever()

    async def shutdown(self) -> None:
        """Stop accepting, close IDLE connections, drain in-flight requests.

        Idle keep-alive connections sit in a read for up to 75s, so their
        transports close immediately; connections with a request mid-handler
        get up to ``drain_timeout_s`` to finish and flush their response
        (queued batched inference makes this drain mandatory — SURVEY §7).
        Requires Python ≥3.12 semantics for ``Server.wait_closed()`` (waits
        for handlers); on older runtimes the explicit in-flight poll below
        still provides the drain.
        """
        if self._server is None:
            return
        self._server.close()
        for writer in list(self._conns):
            if writer in self._inflight:
                continue
            try:
                writer.close()
            except Exception:
                pass
        deadline = asyncio.get_running_loop().time() + self.drain_timeout_s
        while self._inflight and asyncio.get_running_loop().time() < deadline:
            await asyncio.sleep(0.05)
        if self._inflight:
            if self._logger is not None:
                self._logger.warnf(
                    "shutdown drain timed out with %d in-flight request(s); closing",
                    len(self._inflight),
                )
            for writer in list(self._inflight):
                try:
                    writer.close()
                except Exception:
                    pass
        try:
            await asyncio.wait_for(self._server.wait_closed(), timeout=5)
        except asyncio.TimeoutError:
            if self._logger is not None:
                self._logger.warn("wait_closed timed out; continuing shutdown")

    async def _serve_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        self._conns.add(writer)
        try:
            first = True
            while True:
                timeout = READ_HEADER_TIMEOUT_S if first else KEEPALIVE_IDLE_TIMEOUT_S
                # Read the request line here so the connection counts as
                # in-flight from the first byte of a request — a slow upload
                # mid-shutdown drains instead of being reset.
                try:
                    line = await asyncio.wait_for(reader.readline(), timeout)
                except (asyncio.TimeoutError, ConnectionResetError):
                    break
                if not line:
                    break
                self._inflight.add(writer)
                try:
                    try:
                        raw = await asyncio.wait_for(
                            read_request(reader, peer=peer, first_line=line),
                            BODY_READ_TIMEOUT_S,
                        )
                    except asyncio.TimeoutError:
                        break
                    except (asyncio.IncompleteReadError, ConnectionResetError):
                        break
                    except ProtocolError as exc:
                        writer.write(
                            serialize_response(
                                Response(
                                    status=exc.status,
                                    headers={"Content-Type": "text/plain"},
                                    body=str(exc).encode(),
                                ),
                                keep_alive=False,
                            )
                        )
                        await _safe_drain(writer)
                        break
                    if raw is None:
                        break
                    first = False
                    # Request lifecycle: a cancel token every layer below
                    # can share (the OpenAI routes hand it to the engine),
                    # tripped when this connection dies mid-request; an
                    # optional client deadline from X-Request-Timeout.
                    cancel = CancelToken()
                    raw.ctx_data["cancel"] = cancel
                    tmo = raw.headers.get(REQUEST_TIMEOUT_HEADER)
                    if tmo:
                        try:
                            raw.ctx_data["deadline"] = Deadline.after(
                                float(tmo)
                            )
                        except ValueError:
                            if self._logger is not None:
                                self._logger.warnf(
                                    "ignoring malformed %s header: %r",
                                    REQUEST_TIMEOUT_HEADER, tmo,
                                )
                    # Disconnect watch for the handler's whole run (not
                    # just the response write): a client that vanishes
                    # mid-generation must trip the cancel token NOW so
                    # the engine's lifecycle reap frees its KV slot,
                    # instead of decoding the full budget for nobody.
                    # Poll-based (at_eof/is_closing) on purpose — reading
                    # the socket to detect EOF would consume a pipelined
                    # next request's bytes.
                    watcher = asyncio.ensure_future(
                        _watch_disconnect(reader, writer, cancel)
                    )
                    try:
                        resp = await self._handler(raw)
                    except Exception as exc:  # framework-level last resort
                        if self._logger is not None:
                            self._logger.errorf("unhandled server error: %s", exc)
                        resp = Response(
                            status=500,
                            headers={"Content-Type": "application/json"},
                            body=b'{"error":{"message":"Internal Server Error"}}',
                        )
                    finally:
                        watcher.cancel()

                    keep = raw.keep_alive
                    writer.write(
                        serialize_response(
                            resp, head_only=(raw.method == "HEAD"), keep_alive=keep
                        )
                    )
                    drained = await _safe_drain(writer)
                    if (
                        drained
                        and resp.body_stream is not None
                        and raw.method != "HEAD"
                    ):
                        # Chunked streaming body (SSE): write chunks as
                        # the handler's async iterator yields them. A
                        # failed write mid-stream closes the connection
                        # (the client can't distinguish a truncated
                        # chunked body from completion otherwise).
                        try:
                            async for chunk in resp.body_stream:
                                if not chunk:
                                    continue
                                writer.write(chunk_encode(chunk))
                                if not await _safe_drain(writer):
                                    keep = False
                                    break
                            else:
                                writer.write(CHUNKED_TERMINATOR)
                                drained = await _safe_drain(writer)
                        except Exception as exc:  # noqa: BLE001
                            if self._logger is not None:
                                self._logger.errorf(
                                    "stream body failed: %s", exc
                                )
                            keep = False
                        finally:
                            # Disconnect mid-stream: close the generator
                            # NOW so GeneratorExit reaches the handler
                            # (which can cancel the generation feeding
                            # it) instead of at GC time.
                            aclose = getattr(resp.body_stream, "aclose", None)
                            if aclose is not None:
                                try:
                                    await aclose()
                                except Exception:  # noqa: BLE001
                                    pass
                finally:
                    self._inflight.discard(writer)
                if not drained:
                    # The client is gone mid-response: trip the request's
                    # cancel token so any engine work feeding it retires
                    # (the stream path's aclose above handles SSE; this
                    # covers responses that failed to flush).
                    cancel.cancel()
                if not drained or not keep:
                    break
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass


async def _watch_disconnect(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    cancel: CancelToken,
    poll_s: float = 0.25,
) -> None:
    """Trip ``cancel`` when the peer goes away while a handler is
    running. A FIN surfaces as ``reader.at_eof()`` (the event loop keeps
    the socket read-registered while the handler awaits), an RST as a
    closing transport. Half-close clients (shutdown(WR) then read the
    response) are treated as disconnects — pathological under HTTP/1.1
    keep-alive. Cancelled by the caller when the handler returns; fast
    handlers (health, metrics) therefore never reach the first poll, and
    the interval is sized so 64 concurrent generations cost ~256 timer
    wakeups/sec, not thousands — disconnect reaping only needs to beat
    the decode budget, not the millisecond."""
    while True:
        if reader.at_eof() or writer.is_closing():
            cancel.cancel()
            return
        await asyncio.sleep(poll_s)


async def _safe_drain(writer: asyncio.StreamWriter) -> bool:
    try:
        await writer.drain()
        return True
    except (ConnectionResetError, BrokenPipeError):
        return False
