"""Embedded static assets (reference ``pkg/gofr/static/files.go:5-8`` embeds a
favicon via embed.FS). A minimal valid 16x16 1-bit ICO, generated in code to
keep the repo binary-free."""

import struct


def _build_favicon() -> bytes:
    # ICO header + one 16x16 monochrome BMP entry.
    width = height = 16
    # BITMAPINFOHEADER (height doubled for XOR+AND masks)
    bmp_header = struct.pack(
        "<IiiHHIIiiII", 40, width, height * 2, 1, 1, 0, 0, 0, 0, 2, 0
    )
    palette = struct.pack("<II", 0x00000000, 0x00FFFFFF)  # black, white
    # XOR mask: simple "T" glyph (TPU), 16 rows bottom-up, 4 bytes/row padding.
    rows = []
    glyph = [
        0b0000000000000000,
        0b0000000000000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0000001111000000,
        0b0011111111111100,
        0b0011111111111100,
        0b0011111111111100,
        0b0000000000000000,
        0b0000000000000000,
        0b0000000000000000,
    ]
    for row in reversed(glyph):
        rows.append(struct.pack(">H", row) + b"\x00\x00")
    xor_mask = b"".join(rows)
    and_mask = (b"\x00\x00\x00\x00") * height  # all visible
    image = bmp_header + palette + xor_mask + and_mask
    icondir = struct.pack("<HHH", 0, 1, 1)
    entry = struct.pack(
        "<BBBBHHII", width, height, 2, 0, 1, 1, len(image), 6 + 16
    )
    return icondir + entry + image


FAVICON = _build_favicon()
