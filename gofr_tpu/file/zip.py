"""In-memory zip handling with a decompression-bomb guard
(reference ``file/zip.go:13-109``: 100MB total cap).
"""

from __future__ import annotations

import io
import os
import zipfile

MAX_TOTAL_UNCOMPRESSED = 100 * 1024 * 1024  # reference file/zip.go:13-15


class ZipBombError(Exception):
    status_code = 413

    def __init__(self) -> None:
        super().__init__("zip contents exceed the 100MB safety limit")


class Zip:
    """Reads a zip archive fully into memory, per-file bytes by name."""

    def __init__(self, content: bytes) -> None:
        self.files: dict[str, bytes] = {}
        with zipfile.ZipFile(io.BytesIO(content)) as zf:
            total = sum(info.file_size for info in zf.infolist())
            if total > MAX_TOTAL_UNCOMPRESSED:
                raise ZipBombError()
            for info in zf.infolist():
                if info.is_dir():
                    continue
                self.files[info.filename] = zf.read(info)

    def create_local_copies(self, dest_dir: str) -> list[str]:
        """Write contents to disk (reference ``file/file.go:3-24``), guarding
        against path traversal."""
        written = []
        for name, data in self.files.items():
            safe = os.path.normpath(name)
            if safe.startswith("..") or os.path.isabs(safe):
                continue
            path = os.path.join(dest_dir, safe)
            os.makedirs(os.path.dirname(path) or dest_dir, exist_ok=True)
            with open(path, "wb") as fp:
                fp.write(data)
            written.append(path)
        return written
