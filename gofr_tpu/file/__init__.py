"""File helpers (reference: ``pkg/gofr/file``)."""

from gofr_tpu.file.zip import Zip, ZipBombError

__all__ = ["Zip", "ZipBombError"]
