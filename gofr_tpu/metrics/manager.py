"""Metrics manager and instrument registry.

Capability parity with the reference's ``metrics/register.go:15-270``:

* ``new_counter`` / ``new_updown_counter`` / ``new_histogram`` / ``new_gauge``
  register instruments by name (duplicate registration logs an error);
* ``increment_counter`` / ``delta_updown_counter`` / ``record_histogram`` /
  ``set_gauge`` record by name with key=value labels;
* labels must come in pairs and recording on an unregistered name logs an
  error instead of raising (reference ``register.go:168-247``);
* a cardinality warning fires when a metric exceeds 20 distinct label sets
  (reference ``register.go:249-270``);
* gauges are *settable* synchronous gauges keyed by label set — the reference
  built a custom callback gauge for exactly this (``register.go:41-43``).

TPU-first deltas: locking is per-instrument so unrelated metrics never
contend on the request/decode hot path, and the serving engine registers
per-chip gauges (queue depth, HBM used) on the same registry.

This module is in the strict-mypy scope (pyproject ``[tool.mypy]``).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Optional, Sequence

_CARDINALITY_WARN_AT = 20

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 7.5, 10.0,
)

#: A recorded label set: sorted ((key, value), ...) pairs.
LabelSet = tuple[tuple[str, str], ...]

#: One histogram series: (per-bucket counts, [sum, count]).
HistogramSeries = tuple[list[int], list[float]]


def _labelset(labels: tuple) -> LabelSet:
    if len(labels) % 2 != 0:
        raise ValueError("labels must be key/value pairs")
    pairs = [(str(labels[i]), str(labels[i + 1])) for i in range(0, len(labels), 2)]
    pairs.sort()
    return tuple(pairs)


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, description: str) -> None:
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._series: dict[LabelSet, Any] = {}

    def labelsets(self) -> list[LabelSet]:
        with self._lock:
            return list(self._series.keys())


class Counter(_Instrument):
    kind = "counter"

    def add(self, value: float, labels: tuple) -> None:
        key = _labelset(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def collect(self) -> dict[LabelSet, float]:
        with self._lock:
            return dict(self._series)


class UpDownCounter(Counter):
    kind = "gauge"  # prometheus has no signed counter; exposed as gauge


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, labels: tuple) -> None:
        key = _labelset(labels)
        with self._lock:
            self._series[key] = float(value)

    def collect(self) -> dict[LabelSet, float]:
        with self._lock:
            return dict(self._series)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name: str, description: str, buckets: Sequence[float]) -> None:
        super().__init__(name, description)
        self.buckets: tuple[float, ...] = tuple(sorted(buckets))

    def record(self, value: float, labels: tuple) -> None:
        key = _labelset(labels)
        with self._lock:
            series: Optional[HistogramSeries] = self._series.get(key)
            if series is None:
                # bucket counts, (sum, count)
                series = [0] * (len(self.buckets) + 1), [0.0, 0]
                self._series[key] = series
            counts, agg = series
            # Prometheus `le` is inclusive: first bucket with bound >= value.
            idx = bisect_left(self.buckets, value)
            counts[min(idx, len(counts) - 1)] += 1
            agg[0] += value
            agg[1] += 1

    def collect(self) -> dict[LabelSet, tuple[list[int], tuple[float, float]]]:
        with self._lock:
            return {
                key: ([*counts], (agg[0], agg[1]))
                for key, (counts, agg) in self._series.items()
            }


class Manager:
    """Thread-safe instrument registry (reference ``metrics/register.go:15-25``)."""

    def __init__(self, logger: Any = None) -> None:
        self._logger = logger
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}
        self._warned: set[str] = set()

    # -- registration (reference register.go:62-145) ---------------------

    def _register(self, inst: _Instrument) -> None:
        with self._lock:
            if inst.name in self._instruments:
                self._log_error(f"metrics {inst.name} already registered")
                return
            self._instruments[inst.name] = inst

    def new_counter(self, name: str, description: str = "") -> None:
        self._register(Counter(name, description))

    def new_updown_counter(self, name: str, description: str = "") -> None:
        self._register(UpDownCounter(name, description))

    def new_histogram(
        self, name: str, description: str = "", buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> None:
        self._register(Histogram(name, description, buckets))

    def new_gauge(self, name: str, description: str = "") -> None:
        self._register(Gauge(name, description))

    # -- recording (reference register.go:168-247) -----------------------

    def _get(self, name: str, cls: type) -> Optional[_Instrument]:
        inst = self._instruments.get(name)
        if inst is None:
            self._log_error(f"metrics {name} is not registered")
            return None
        # Exact-type match: an UpDownCounter may not be used as a Counter.
        if type(inst) is not cls:
            self._log_error(f"metrics {name} is not of type {cls.__name__}")
            return None
        return inst

    def increment_counter(self, name: str, *labels: Any) -> None:
        inst = self._get(name, Counter)
        if not isinstance(inst, Counter):
            return
        try:
            inst.add(1.0, labels)
        except ValueError as exc:
            self._log_error(f"metrics {name}: {exc}")
            return
        self._check_cardinality(inst)

    def add_counter(self, name: str, value: float, *labels: Any) -> None:
        """Increment a counter by ``value`` (>0) — token-denominated
        counters (e.g. prefix hit tokens) add per-request amounts in one
        call instead of N increments."""
        inst = self._get(name, Counter)
        if not isinstance(inst, Counter):
            return
        try:
            inst.add(float(value), labels)
        except ValueError as exc:
            self._log_error(f"metrics {name}: {exc}")
            return
        self._check_cardinality(inst)

    def delta_updown_counter(self, name: str, value: float, *labels: Any) -> None:
        inst = self._get(name, UpDownCounter)
        if not isinstance(inst, UpDownCounter):
            return
        try:
            inst.add(value, labels)
        except ValueError as exc:
            self._log_error(f"metrics {name}: {exc}")
            return
        self._check_cardinality(inst)

    def record_histogram(self, name: str, value: float, *labels: Any) -> None:
        inst = self._get(name, Histogram)
        if not isinstance(inst, Histogram):
            return
        try:
            inst.record(value, labels)
        except ValueError as exc:
            self._log_error(f"metrics {name}: {exc}")
            return
        self._check_cardinality(inst)

    def set_gauge(self, name: str, value: float, *labels: Any) -> None:
        inst = self._get(name, Gauge)
        if not isinstance(inst, Gauge):
            return
        try:
            inst.set(value, labels)
        except ValueError as exc:
            self._log_error(f"metrics {name}: {exc}")
            return
        self._check_cardinality(inst)

    def _check_cardinality(self, inst: _Instrument) -> None:
        # Reference register.go:249-270 warns above 20 distinct label sets.
        if inst.name in self._warned:
            return
        if len(inst._series) > _CARDINALITY_WARN_AT:
            self._warned.add(inst.name)
            if self._logger is not None:
                self._logger.warnf(
                    "metric %s has high cardinality: %d label sets",
                    inst.name,
                    len(inst._series),
                )

    def _log_error(self, msg: str) -> None:
        if self._logger is not None:
            self._logger.error(msg)

    # -- collection ------------------------------------------------------

    def instruments(self) -> list[_Instrument]:
        with self._lock:
            return list(self._instruments.values())


def new_metrics_manager(logger: Any = None) -> Manager:
    """Reference ``metrics/register.go:49-55``."""
    return Manager(logger=logger)
