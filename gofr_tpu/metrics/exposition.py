"""Prometheus text-format exposition.

Renders a :class:`gofr_tpu.metrics.manager.Manager` registry in Prometheus
text format v0.0.4 — the role the reference delegates to the OTel prometheus
reader + promhttp (``metrics/exporters/exporter.go:14-29``,
``metrics/handler.go:12-19``). Includes per-scrape process/runtime gauges,
mirroring ``metrics/handler.go:21-35`` (goroutines/heap/GC there; here
threads, RSS, GC stats, plus accelerator device count).
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Iterable

from gofr_tpu.metrics.manager import Counter, Gauge, Histogram, Manager, UpDownCounter
from gofr_tpu.version import FRAMEWORK_VERSION

_START_TIME = time.time()


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(pairs: Iterable[tuple[str, str]], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in pairs]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as fp:
            return int(fp.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return 0


def render_prometheus(manager: Manager, app_name: str = "gofr-tpu-app") -> str:
    out: list[str] = []
    # Per-scrape runtime stats (reference metrics/handler.go:21-35).
    gc_counts = gc.get_count()
    runtime: dict[str, float] = {
        "process_threads": threading.active_count(),
        "process_resident_memory_bytes": _rss_bytes(),
        "process_uptime_seconds": time.time() - _START_TIME,
        "python_gc_gen0_collections": gc.get_stats()[0].get("collections", 0),
        "python_gc_objects_tracked": sum(gc_counts),
    }
    out.append(
        f'# HELP app_info build/runtime info\n# TYPE app_info gauge\n'
        f'app_info{{app="{_escape(app_name)}",framework_version="{FRAMEWORK_VERSION}"}} 1\n'
    )
    for name, val in runtime.items():
        out.append(f"# TYPE {name} gauge\n{name} {val}\n")

    for inst in manager.instruments():
        if inst.description:
            out.append(f"# HELP {inst.name} {_escape(inst.description)}\n")
        out.append(f"# TYPE {inst.name} {inst.kind}\n")
        if isinstance(inst, Histogram):
            for key, (counts, (total, count)) in inst.collect().items():
                cumulative = 0
                for bound, c in zip(inst.buckets, counts):
                    cumulative += c
                    le = 'le="' + str(bound) + '"'
                    out.append(f"{inst.name}_bucket{_fmt_labels(key, le)} {cumulative}\n")
                cumulative += counts[-1]
                # NB: hoisted out of the f-string — a backslash inside an
                # f-string expression is a SyntaxError before Python 3.12.
                le_inf = 'le="+Inf"'
                out.append(
                    f"{inst.name}_bucket{_fmt_labels(key, le_inf)} {cumulative}\n"
                )
                out.append(f"{inst.name}_sum{_fmt_labels(key)} {total}\n")
                out.append(f"{inst.name}_count{_fmt_labels(key)} {count}\n")
        elif isinstance(inst, (Counter, UpDownCounter, Gauge)):
            for key, val in inst.collect().items():
                out.append(f"{inst.name}{_fmt_labels(key)} {val}\n")
    return "".join(out)
