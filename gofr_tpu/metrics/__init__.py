"""Metrics layer (reference: ``pkg/gofr/metrics``).

A name→instrument registry with counter / up-down counter / histogram /
settable gauge, label validation, cardinality warnings, and Prometheus text
exposition — the capability set of the reference's ``metrics/register.go`` +
``metrics/exporters/exporter.go``, implemented natively (no OTel SDK on the
hot path).
"""

from gofr_tpu.metrics.manager import Manager, new_metrics_manager
from gofr_tpu.metrics.exposition import render_prometheus

__all__ = ["Manager", "new_metrics_manager", "render_prometheus"]
