"""At-least-once pubsub for the serving plane (ISSUE 18).

This package is the serving side's broker abstraction — distinct from
``gofr_tpu/datasource/pubsub`` (the GoFr-compatible fire-and-forget
``Subscribe`` surface) because inference work needs *delivery
semantics*: explicit ack/nack leases, lease-expiry redelivery, and
crash-safe resumption. Two brokers ship:

* :class:`~gofr_tpu.pubsub.broker.InMemoryBroker` — deterministic
  (injectable clock, no timers, no threads) for tests and CPU runs;
* :class:`~gofr_tpu.pubsub.durable.DurableBroker` — the same core
  behind an append-only per-topic op journal, so a process crash
  resumes with every unacked message ready again (at-least-once).

``make_broker`` is the config seam (``TPU_ASYNC_BROKER=memory|file``).
"""

from gofr_tpu.pubsub.broker import (
    InMemoryBroker,
    LeasedMessage,
    Subscription,
    make_broker,
)
from gofr_tpu.pubsub.durable import DurableBroker

__all__ = [
    "DurableBroker",
    "InMemoryBroker",
    "LeasedMessage",
    "Subscription",
    "make_broker",
]
