"""The file-backed durable broker: single-host crash-safe resumption.

One append-only JSONL journal per topic (``<dir>/<topic>.jsonl``; topic
names are sanitized into filenames). Every mutating op — ``pub``,
``lease``, ``ack``, ``nack`` — is appended and flushed before the call
returns, and construction replays the journals front to back:

* published but unacked  → ready again (leases are volatile by design —
  a crashed consumer's lease dies with its process, which IS the
  at-least-once redelivery path);
* acked                  → gone;
* lease count            → preserved, so a consumer that crash-loops on
  a poison message still exhausts its redelivery budget and the message
  still reaches the dead-letter topic.

``compact()`` rewrites a journal to just the live messages — the bound
on journal growth for long-running hosts. Durability is flush-on-append
(``fsync=True`` upgrades to fsync for hosts that need power-loss
safety at the cost of per-op latency).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Callable, IO, Optional

from gofr_tpu.pubsub.broker import InMemoryBroker

_SAFE = re.compile(r"[^A-Za-z0-9_.-]")


def _topic_file(dir_: str, topic: str) -> str:
    return os.path.join(dir_, _SAFE.sub("_", topic) + ".jsonl")


class DurableBroker(InMemoryBroker):
    """The in-memory core behind a per-topic op journal."""

    def __init__(
        self,
        dir: str,
        *,
        clock: Callable[[], float] = time.monotonic,
        fsync: bool = False,
    ) -> None:
        super().__init__(clock=clock)
        self.dir = dir
        self._fsync = fsync
        self._files: dict[str, IO[str]] = {}
        self._replaying = False
        os.makedirs(dir, exist_ok=True)
        self._replay_all()

    # -- journal ---------------------------------------------------------

    def _journal(self, topic: str, op: dict[str, Any]) -> None:
        if self._replaying:
            return
        f = self._files.get(topic)
        if f is None:
            f = open(  # noqa: SIM115 — held open across ops, closed in close()
                _topic_file(self.dir, topic), "a", encoding="utf-8"
            )
            self._files[topic] = f
        f.write(json.dumps(op, separators=(",", ":")) + "\n")
        f.flush()
        if self._fsync:
            os.fsync(f.fileno())

    def _replay_all(self) -> None:
        self._replaying = True
        try:
            for name in sorted(os.listdir(self.dir)):
                if not name.endswith(".jsonl"):
                    continue
                topic = name[: -len(".jsonl")]
                path = os.path.join(self.dir, name)
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            op = json.loads(line)
                        except ValueError:
                            continue  # torn tail write mid-crash
                        if isinstance(op, dict):
                            self._replay_op(topic, op)
        finally:
            self._replaying = False

    # -- maintenance -----------------------------------------------------

    def compact(self, topic: str) -> int:
        """Rewrite ``topic``'s journal to just its live messages (one
        ``pub`` plus ``attempt`` leases each); returns the live count.
        Safe at any quiet point — the rewritten journal replays to the
        same state the broker holds now."""
        entries = self.peek_all(topic)
        path = _topic_file(self.dir, topic)
        old = self._files.pop(topic, None)
        if old is not None:
            old.close()
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for e in entries:
                f.write(json.dumps(
                    {"op": "pub", "id": e.id, "value": e.value,
                     "headers": e.headers},
                    separators=(",", ":"),
                ) + "\n")
                for _ in range(e.attempt):
                    f.write(json.dumps(
                        {"op": "lease", "id": e.id},
                        separators=(",", ":"),
                    ) + "\n")
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(entries)

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()


def open_durable(
    dir: str, clock: Optional[Callable[[], float]] = None
) -> DurableBroker:
    """Convenience constructor mirroring ``make_broker("file", ...)``."""
    return DurableBroker(dir, clock=clock or time.monotonic)
