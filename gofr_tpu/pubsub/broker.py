"""The lease-based broker core: at-least-once, deterministic, bounded.

Delivery model (the contract ``serving/async_serving.py`` builds on):

* ``publish`` appends a message to a topic and returns its id (caller
  may pin one — idempotent replays reuse the same id).
* ``subscribe`` returns a :class:`Subscription`; ``lease()`` hands out
  the oldest *ready* message and starts a lease clock. A message is
  redelivered when its lease expires (consumer died) or it is nacked
  (consumer failed); ``ack`` retires it for good.
* ``attempt`` counts *deliveries* (increments at lease time), so a
  redelivery budget reads directly off the message. A drain-time nack
  may set ``penalize=False`` so handing work back does not burn the
  message's budget.
* Every lifecycle event is appended to the message's bounded
  ``history`` ring — the redelivery record the dead-letter annotation
  carries.

Determinism rules (the repo-wide discipline): an injectable clock, no
timers and no broker threads — expired leases are collected lazily at
the next ``lease()`` call, so tests *state* time instead of sleeping.
"""

from __future__ import annotations

import heapq
import time
from typing import Any, Callable, Optional

from gofr_tpu.analysis import lockcheck

#: Per-message lifecycle-history bound: enough for a full redelivery
#: budget's worth of lease/nack pairs without unbounded growth on a
#: message that flaps for hours.
HISTORY_MAX = 64


class LeasedMessage:
    """One delivery: an immutable view handed to the consumer. ``ack``/
    ``nack`` go through the :class:`Subscription` keyed by ``id``."""

    __slots__ = (
        "id", "topic", "value", "headers", "attempt", "enqueued_at",
        "history",
    )

    def __init__(
        self,
        id: str,
        topic: str,
        value: str,
        headers: dict[str, str],
        attempt: int,
        enqueued_at: float,
        history: list[dict[str, Any]],
    ) -> None:
        self.id = id
        self.topic = topic
        self.value = value
        self.headers = headers
        #: Deliveries so far, THIS one included (1 = first delivery).
        self.attempt = attempt
        self.enqueued_at = enqueued_at
        #: Copy of the lifecycle ring at lease time (DLQ annotation).
        self.history = history


class _Entry:
    """A message's broker-side state."""

    __slots__ = (
        "id", "value", "headers", "attempt", "enqueued_at", "ready_at",
        "lease_expires_at", "history",
    )

    def __init__(
        self, id: str, value: str, headers: dict[str, str], now: float
    ) -> None:
        self.id = id
        self.value = value
        self.headers = headers
        self.attempt = 0
        self.enqueued_at = now
        self.ready_at: float = now
        #: None = ready (not leased).
        self.lease_expires_at: Optional[float] = None
        self.history: list[dict[str, Any]] = []

    def note(self, event: str, now: float, **attrs: Any) -> None:
        self.history.append({"event": event, "at": round(now, 3), **attrs})
        if len(self.history) > HISTORY_MAX:
            del self.history[: len(self.history) - HISTORY_MAX]


class _Topic:
    __slots__ = ("entries", "heap", "seq")

    def __init__(self) -> None:
        self.entries: dict[str, _Entry] = {}
        #: Lazy-deletion min-heap of (ready_at, seq, id) over READY
        #: entries; leased/acked ids are skipped at pop time.
        self.heap: list[tuple[float, int, str]] = []
        self.seq = 0


class Subscription:
    """One consumer's handle on a topic: ``lease``/``ack``/``nack``.

    Leases are process-volatile by design — a consumer crash simply
    stops renewing them, and every unacked message returns to ready
    when its lease clock runs out (the at-least-once half of the
    contract; the consumer's dedup ledger supplies the other half).
    """

    def __init__(
        self, broker: "InMemoryBroker", topic: str, lease_s: float
    ) -> None:
        self._broker = broker
        self.topic = topic
        self.lease_s = max(0.001, float(lease_s))

    def lease(self) -> Optional[LeasedMessage]:
        """The oldest ready message, leased for ``lease_s`` — or None
        when the topic has nothing ready (never blocks)."""
        return self._broker._lease(self.topic, self.lease_s)

    def ack(self, msg_id: str) -> bool:
        """Retire ``msg_id`` for good. False = unknown id (already
        acked, or re-leased after this consumer's lease expired)."""
        return self._broker._ack(self.topic, msg_id)

    def nack(
        self,
        msg_id: str,
        *,
        delay_s: float = 0.0,
        note: str = "",
        penalize: bool = True,
    ) -> bool:
        """Hand ``msg_id`` back: ready again after ``delay_s``.
        ``penalize=False`` (graceful drain) refunds the delivery so the
        redelivery budget only counts real failures."""
        return self._broker._nack(
            self.topic, msg_id, delay_s=delay_s, note=note,
            penalize=penalize,
        )

    def inflight(self) -> int:
        """Messages currently leased (not yet acked/nacked/expired)."""
        return self._broker.inflight(self.topic)


class InMemoryBroker:
    """The deterministic single-process broker (module docstring)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._lock = lockcheck.make_lock("InMemoryBroker._lock")
        self._topics: dict[str, _Topic] = {}
        self._published = 0

    # -- journal hook (DurableBroker overrides) ------------------------

    def _journal(self, topic: str, op: dict[str, Any]) -> None:
        """Persistence seam: the in-memory broker keeps nothing."""

    # -- producer surface ----------------------------------------------

    def publish(
        self,
        topic: str,
        value: str,
        headers: Optional[dict[str, str]] = None,
        *,
        message_id: Optional[str] = None,
    ) -> str:
        """Append one message; returns its id. A pinned ``message_id``
        that already exists on the topic is a no-op returning the same
        id — publish is idempotent per id, the replay-safety seam the
        consumer's dedup ledger keys on."""
        now = self._clock()
        with self._lock:
            t = self._topics.setdefault(topic, _Topic())
            self._published += 1
            mid = message_id or f"{topic}-{self._published:08d}"
            if mid in t.entries:
                return mid
            entry = _Entry(mid, value, dict(headers or {}), now)
            entry.note("published", now)
            t.entries[mid] = entry
            t.seq += 1
            heapq.heappush(t.heap, (entry.ready_at, t.seq, mid))
            self._journal(topic, {
                "op": "pub", "id": mid, "value": value,
                "headers": entry.headers,
            })
            return mid

    # -- consumer surface (via Subscription) ---------------------------

    def subscribe(self, topic: str, *, lease_s: float = 30.0) -> Subscription:
        with self._lock:
            self._topics.setdefault(topic, _Topic())
        return Subscription(self, topic, lease_s)

    def _collect_expired(self, t: _Topic, now: float) -> None:
        """Return every expired lease to ready (call under the lock).
        Lazy — runs at lease time, so expiry needs no broker thread."""
        for entry in t.entries.values():
            exp = entry.lease_expires_at
            if exp is not None and exp <= now:
                entry.lease_expires_at = None
                entry.ready_at = now
                entry.note("lease_expired", now, attempt=entry.attempt)
                t.seq += 1
                heapq.heappush(t.heap, (now, t.seq, entry.id))

    def _lease(self, topic: str, lease_s: float) -> Optional[LeasedMessage]:
        now = self._clock()
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                return None
            self._collect_expired(t, now)
            while t.heap:
                ready_at, _seq, mid = t.heap[0]
                if ready_at > now:
                    return None
                heapq.heappop(t.heap)
                entry = t.entries.get(mid)
                if (
                    entry is None
                    or entry.lease_expires_at is not None
                    or entry.ready_at > now
                ):
                    continue  # acked, re-leased, or re-scheduled later
                entry.attempt += 1
                entry.lease_expires_at = now + lease_s
                entry.note("leased", now, attempt=entry.attempt)
                self._journal(topic, {"op": "lease", "id": mid})
                return LeasedMessage(
                    entry.id, topic, entry.value, dict(entry.headers),
                    entry.attempt, entry.enqueued_at,
                    list(entry.history),
                )
            return None

    def _ack(self, topic: str, msg_id: str) -> bool:
        with self._lock:
            t = self._topics.get(topic)
            if t is None or msg_id not in t.entries:
                return False
            del t.entries[msg_id]
            self._journal(topic, {"op": "ack", "id": msg_id})
            return True

    def _nack(
        self,
        topic: str,
        msg_id: str,
        *,
        delay_s: float,
        note: str,
        penalize: bool,
    ) -> bool:
        now = self._clock()
        with self._lock:
            t = self._topics.get(topic)
            entry = t.entries.get(msg_id) if t is not None else None
            if t is None or entry is None:
                return False
            entry.lease_expires_at = None
            entry.ready_at = now + max(0.0, float(delay_s))
            if not penalize:
                entry.attempt = max(0, entry.attempt - 1)
            entry.note(
                "nacked", now, delay_s=round(max(0.0, delay_s), 3),
                note=note, penalize=penalize,
            )
            t.seq += 1
            heapq.heappush(t.heap, (entry.ready_at, t.seq, msg_id))
            self._journal(topic, {
                "op": "nack", "id": msg_id,
                "delay_s": max(0.0, float(delay_s)), "note": note,
                "penalize": penalize,
            })
            return True

    # -- introspection --------------------------------------------------

    def depth(self, topic: str) -> int:
        """Ready (unleased) messages — the consumer-lag signal."""
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                return 0
            return sum(
                1 for e in t.entries.values()
                if e.lease_expires_at is None
            )

    def inflight(self, topic: str) -> int:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                return 0
            return sum(
                1 for e in t.entries.values()
                if e.lease_expires_at is not None
            )

    def size(self, topic: str) -> int:
        """All live messages on the topic (ready + leased)."""
        with self._lock:
            t = self._topics.get(topic)
            return 0 if t is None else len(t.entries)

    def topics(self) -> list[str]:
        with self._lock:
            return sorted(self._topics)

    def peek_all(self, topic: str) -> list[LeasedMessage]:
        """Non-mutating snapshot of a topic (tests / debug surface) —
        leases and attempts are untouched."""
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                return []
            return [
                LeasedMessage(
                    e.id, topic, e.value, dict(e.headers), e.attempt,
                    e.enqueued_at, list(e.history),
                )
                for e in t.entries.values()
            ]

    def close(self) -> None:
        """Release any persistence resources (no-op in memory)."""

    # -- replay seam (DurableBroker) ------------------------------------

    def _replay_op(self, topic: str, op: dict[str, Any]) -> None:
        """Apply one journaled op to the in-memory state WITHOUT
        re-journaling. Replay semantics are the crash contract: every
        unacked message comes back *ready* (leases are volatile) with
        its delivery count preserved, so a crash-looping consumer still
        runs out of redelivery budget."""
        now = self._clock()
        t = self._topics.setdefault(topic, _Topic())
        kind = op.get("op")
        mid = str(op.get("id", ""))
        if kind == "pub":
            if mid in t.entries:
                return
            entry = _Entry(
                mid, str(op.get("value", "")),
                dict(op.get("headers") or {}), now,
            )
            entry.note("replayed", now)
            t.entries[mid] = entry
            t.seq += 1
            heapq.heappush(t.heap, (entry.ready_at, t.seq, mid))
        elif kind == "lease":
            entry_l = t.entries.get(mid)
            if entry_l is not None:
                entry_l.attempt += 1
        elif kind == "ack":
            t.entries.pop(mid, None)
        elif kind == "nack":
            entry_n = t.entries.get(mid)
            if entry_n is not None and not bool(op.get("penalize", True)):
                entry_n.attempt = max(0, entry_n.attempt - 1)


def make_broker(
    kind: str,
    *,
    dir: str = "",
    clock: Callable[[], float] = time.monotonic,
) -> InMemoryBroker:
    """The ``TPU_ASYNC_BROKER`` seam: ``memory`` (default) or ``file``
    (requires ``TPU_ASYNC_BROKER_DIR``)."""
    kind = (kind or "memory").strip().lower()
    if kind in ("", "memory", "inmemory", "mem"):
        return InMemoryBroker(clock=clock)
    if kind == "file":
        if not dir:
            raise ValueError(
                "TPU_ASYNC_BROKER=file requires TPU_ASYNC_BROKER_DIR"
            )
        from gofr_tpu.pubsub.durable import DurableBroker

        return DurableBroker(dir, clock=clock)
    raise ValueError(f"unknown async broker kind {kind!r}")
