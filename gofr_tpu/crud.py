"""REST CRUD handler generator (reference ``pkg/gofr/crud_handlers.go``).

``app.add_rest_handlers(Entity)`` scans a dataclass (field 0 = primary key,
reference ``crud_handlers.go:17-43``), derives the table name by
snake-casing the class name, and registers the five routes with default
SQL-backed handlers built on the dialect query builder. Any of
``create/get_all/get/update/delete`` defined on the entity class override
the defaults (reference ``crud_handlers.go:53-70``).
"""

from __future__ import annotations

import dataclasses
import re

from gofr_tpu.datasource.sql import (
    delete_by_query,
    insert_query,
    select_by_query,
    select_query,
    update_by_query,
)
from gofr_tpu.errors import ErrorEntityNotFound


def to_snake_case(name: str) -> str:
    """CamelCase → snake_case (reference ``crud_handlers.go:246-266``)."""
    s1 = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", s1).lower()


def scan_entity(entity_cls) -> tuple[str, list, str]:
    """Returns (table, fields, primary_key). Field 0 is the PK."""
    if not (isinstance(entity_cls, type) and dataclasses.is_dataclass(entity_cls)):
        raise TypeError("add_rest_handlers requires a dataclass type")
    fields = dataclasses.fields(entity_cls)
    if not fields:
        raise TypeError("entity has no fields")
    cols = [f.metadata.get("db") or to_snake_case(f.name) for f in fields]
    return to_snake_case(entity_cls.__name__), cols, cols[0]


def register_crud_handlers(app, entity_cls) -> None:
    table, cols, pk = scan_entity(entity_cls)
    route = f"/{table}"
    dialect = "sqlite"

    def _dialect(ctx) -> str:
        return ctx.sql.dialect() if ctx.sql is not None else dialect

    def default_create(ctx):
        data = ctx.bind({})
        values = [data.get(c) for c in cols]
        ctx.sql.exec(insert_query(_dialect(ctx), table, cols), *values)
        return f"{entity_cls.__name__} successfully created with id: {data.get(pk)}"

    def default_get_all(ctx):
        return ctx.sql.query(select_query(_dialect(ctx), table))

    def default_get(ctx):
        row = ctx.sql.query_row(
            select_by_query(_dialect(ctx), table, pk), ctx.path_param("id")
        )
        if row is None:
            raise ErrorEntityNotFound(pk, ctx.path_param("id"))
        return row

    def default_update(ctx):
        data = ctx.bind({})
        non_pk = [c for c in cols if c != pk]
        values = [data.get(c) for c in non_pk] + [ctx.path_param("id")]
        result = ctx.sql.exec(
            update_by_query(_dialect(ctx), table, non_pk, pk), *values
        )
        if result.rows_affected == 0:
            raise ErrorEntityNotFound(pk, ctx.path_param("id"))
        return f"{entity_cls.__name__} successfully updated with id: {ctx.path_param('id')}"

    def default_delete(ctx):
        result = ctx.sql.exec(
            delete_by_query(_dialect(ctx), table, pk), ctx.path_param("id")
        )
        if result.rows_affected == 0:
            raise ErrorEntityNotFound(pk, ctx.path_param("id"))
        return f"{entity_cls.__name__} successfully deleted with id: {ctx.path_param('id')}"

    # User overrides win (reference crud_handlers.go:53-70): class-level
    # create/get_all/get/update/delete callables taking (ctx).
    handlers = {
        "create": getattr(entity_cls, "create", None) or default_create,
        "get_all": getattr(entity_cls, "get_all", None) or default_get_all,
        "get": getattr(entity_cls, "get", None) or default_get,
        "update": getattr(entity_cls, "update", None) or default_update,
        "delete": getattr(entity_cls, "delete", None) or default_delete,
    }

    app.add_route("POST", route, handlers["create"])
    app.add_route("GET", route, handlers["get_all"])
    app.add_route("GET", route + "/{id}", handlers["get"])
    app.add_route("PUT", route + "/{id}", handlers["update"])
    app.add_route("DELETE", route + "/{id}", handlers["delete"])
