"""graftlint — TPU-correctness static analysis (net-new subsystem).

The reference framework's credibility rests on correctness tooling
(generated mocks, race-detector CI). A JAX serving stack has a class of
bugs ordinary linters never catch — tracer leaks, silent host↔device
syncs, recompilation hazards, blocking calls on the batcher hot path —
and they are exactly the bugs that cost the most on real TPU hardware.
graftlint is an AST-based rule engine purpose-built for this codebase:

* ``GL001`` host→device sync on hot paths (``.item()``, ``float()``/
  ``int()``/``np.asarray()`` on device arrays in ``serving/``/``ops/``);
* ``GL002`` Python branching on tracer values inside jitted functions;
* ``GL003`` recompilation hazards (mutable static args, shape-derived
  cache keys);
* ``GL004`` blocking calls inside ``async def`` or the batcher/
  scheduler/engine hot path;
* ``GL005`` lock-discipline drift (shared attributes written both under
  and outside a lock) in the threaded serving core;
* ``GL006`` broad exception handlers that silently swallow errors in
  request paths;
* ``GL007`` donated-buffer reuse after ``donate_argnums``;
* ``GL008`` ``jnp.asarray``/``jnp.array`` inside ``lax.scan`` bodies;
* ``GL009`` per-request jit-cache growth (shape-keyed lru_cache/dict
  caches of jit builders);
* ``GL010`` repeated host pulls (``np.asarray``/``jax.device_get``) of
  the same device value inside a loop body.

Rules GL011–GL019 extend the same per-file engine (see the docs); the
project-wide concurrency rules run a second phase over a cross-file
index of the serving thread mesh (``gofr_tpu/analysis/project.py``):

* ``GL020`` unguarded shared state (guarded-by declarations +
  majority-access inference, thread-root reachability);
* ``GL021`` lock-order inversions over the may-acquire-while-holding
  graph, including plain-Lock self-cycles through call chains;
* ``GL022`` blocking calls (device sync, HTTP, sleep, blocking queue
  gets) transitively reachable under a held lock.

Their dynamic counterpart is ``gofr_tpu/analysis/lockcheck.py``: with
``TPU_LOCKCHECK=1`` every serving/service lock built through
``lockcheck.make_lock`` validates the same invariants at runtime.

Run it as ``python -m gofr_tpu.analysis [paths]``; suppress a finding
in place with ``# graftlint: disable=GL001`` and record pre-existing
debt in the committed baseline (``--write-baseline`` /
``--check-baseline``). See ``docs/advanced-guide/static-analysis.md``.
"""

from gofr_tpu.analysis.core import (
    Baseline,
    FileContext,
    Finding,
    LintConfig,
    ProjectRule,
    Rule,
    run_paths,
)
from gofr_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = [
    "ALL_RULES",
    "Baseline",
    "FileContext",
    "Finding",
    "LintConfig",
    "ProjectRule",
    "Rule",
    "default_rules",
    "run_paths",
]
