"""graftlint — TPU-correctness static analysis (net-new subsystem).

The reference framework's credibility rests on correctness tooling
(generated mocks, race-detector CI). A JAX serving stack has a class of
bugs ordinary linters never catch — tracer leaks, silent host↔device
syncs, recompilation hazards, blocking calls on the batcher hot path —
and they are exactly the bugs that cost the most on real TPU hardware.
graftlint is an AST-based rule engine purpose-built for this codebase:

* ``GL001`` host→device sync on hot paths (``.item()``, ``float()``/
  ``int()``/``np.asarray()`` on device arrays in ``serving/``/``ops/``);
* ``GL002`` Python branching on tracer values inside jitted functions;
* ``GL003`` recompilation hazards (mutable static args, shape-derived
  cache keys);
* ``GL004`` blocking calls inside ``async def`` or the batcher/
  scheduler/engine hot path;
* ``GL005`` lock-discipline drift (shared attributes written both under
  and outside a lock) in the threaded serving core;
* ``GL006`` broad exception handlers that silently swallow errors in
  request paths;
* ``GL007`` donated-buffer reuse after ``donate_argnums``;
* ``GL008`` ``jnp.asarray``/``jnp.array`` inside ``lax.scan`` bodies;
* ``GL009`` per-request jit-cache growth (shape-keyed lru_cache/dict
  caches of jit builders);
* ``GL010`` repeated host pulls (``np.asarray``/``jax.device_get``) of
  the same device value inside a loop body.

Run it as ``python -m gofr_tpu.analysis [paths]``; suppress a finding
in place with ``# graftlint: disable=GL001`` and record pre-existing
debt in the committed baseline (``--write-baseline`` /
``--check-baseline``). See ``docs/advanced-guide/static-analysis.md``.
"""

from gofr_tpu.analysis.core import (
    Baseline,
    FileContext,
    Finding,
    LintConfig,
    Rule,
    run_paths,
)
from gofr_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = [
    "ALL_RULES",
    "Baseline",
    "FileContext",
    "Finding",
    "LintConfig",
    "Rule",
    "default_rules",
    "run_paths",
]
