"""graftlint rule engine: findings, suppressions, config, baseline.

Design mirrors the shape of production linters (ruff/pylint) at ~1/100th
the size: a :class:`Rule` walks one file's AST and yields
:class:`Finding`\\ s; the runner parses each file once, applies inline
suppressions and the committed baseline, and reports what is left.

Fingerprints (the baseline keys) hash the *content* of the flagged line,
not its number, so unrelated edits above a finding do not invalidate the
baseline — the same trick ruff's ``--add-noqa``-free baselines and
Pylint's ``--recursive`` caches use.
"""

from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable, Iterator, Optional, Sequence

if TYPE_CHECKING:
    from gofr_tpu.analysis.project import ProjectIndex

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_*]+(?:\s*,\s*[A-Za-z0-9_*]+)*)"
)

_DEFAULT_EXCLUDES = (
    "*_pb2.py",
    "*_pb2_grpc.py",
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule_id: str
    path: str  # repo-relative, posix separators
    line: int  # 1-based
    col: int  # 0-based
    message: str
    code_line: str = ""  # stripped source of ``line`` (fingerprint input)

    def fingerprint(self, occurrence: int = 0) -> str:
        """Stable identity for the baseline: file + rule + line *content*
        (+ disambiguating occurrence index for identical lines), so the
        baseline survives edits that merely shift line numbers."""
        raw = f"{self.path}|{self.rule_id}|{self.code_line}|{occurrence}"
        return hashlib.sha256(raw.encode("utf-8")).hexdigest()[:16]

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col + 1}: "
            f"{self.rule_id} {self.message}"
        )


@dataclass
class FileContext:
    """Everything a rule may need about the file under analysis."""

    path: str  # repo-relative, posix separators
    source: str
    lines: list[str]
    # line number -> set of suppressed rule ids ("*" suppresses all)
    suppressions: dict[int, set[str]]
    # absolute filesystem path (lets rules resolve sibling files, e.g.
    # GL005's cross-file mixin analysis)
    abs_path: str = ""

    def suppressed(self, rule_id: str, line: int) -> bool:
        ids = self.suppressions.get(line)
        return bool(ids) and ("*" in ids or rule_id in ids)


class Rule:
    """Base class for graftlint rules.

    Subclasses set ``rule_id``/``name``/``rationale`` and implement
    :meth:`check`. ``applies_to`` scopes a rule to parts of the tree
    (hot-path rules only fire where the cost is real)."""

    rule_id: str = "GL000"
    name: str = "unnamed"
    rationale: str = ""

    def applies_to(self, path: str) -> bool:
        return True

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: FileContext, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        code = ctx.lines[line - 1].strip() if 0 < line <= len(ctx.lines) else ""
        return Finding(
            rule_id=self.rule_id,
            path=ctx.path,
            line=line,
            col=col,
            message=message,
            code_line=code,
        )


class ProjectRule(Rule):
    """Base class for project-wide rules (the two-phase engine).

    Per-file :class:`Rule`\\ s see one AST at a time; a ``ProjectRule``
    runs *after* every file has been parsed, against the
    :class:`~gofr_tpu.analysis.project.ProjectIndex` the runner builds
    (symbol table, call graph, lock model, thread roots). GL001–GL019
    stay per-file; the GL020+ concurrency rules live here.

    Subclasses implement :meth:`check_project`; :meth:`check` is a
    no-op so a ``ProjectRule`` accidentally passed through the
    per-file path yields nothing rather than crashing."""

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        return iter(())

    def check_project(self, index: "ProjectIndex") -> Iterator[Finding]:
        raise NotImplementedError

    def project_finding(
        self,
        index: "ProjectIndex",
        path: str,
        line: int,
        message: str,
        col: int = 0,
    ) -> Finding:
        ctx = index.files.get(path)
        code = ""
        if ctx is not None and 0 < line <= len(ctx.lines):
            code = ctx.lines[line - 1].strip()
        return Finding(
            rule_id=self.rule_id,
            path=path,
            line=line,
            col=col,
            message=message,
            code_line=code,
        )


@dataclass
class LintConfig:
    """Runtime configuration (CLI flags layered over ``[tool.graftlint]``
    in ``pyproject.toml``)."""

    select: Optional[set[str]] = None  # None = all registered rules
    disable: set[str] = field(default_factory=set)
    exclude: tuple[str, ...] = _DEFAULT_EXCLUDES
    # Rules that only matter where device dispatch happens:
    hot_path_dirs: tuple[str, ...] = ("serving", "ops")
    hot_path_files: tuple[str, ...] = (
        "serving/batcher.py",
        "serving/scheduler.py",
        "serving/engine.py",
    )
    request_path_dirs: tuple[str, ...] = ("serving", "ops", "grpc")
    # Where the thread mesh lives: the project-wide concurrency rules
    # (GL020–GL022) only report findings under these directories.
    concurrency_dirs: tuple[str, ...] = ("serving", "service")

    def wants(self, rule_id: str) -> bool:
        if rule_id in self.disable:
            return False
        return self.select is None or rule_id in self.select


def load_pyproject_config(pyproject_path: str) -> dict:
    """Read ``[tool.graftlint]`` from pyproject.toml.

    Uses :mod:`tomllib` on 3.11+; on older interpreters falls back to a
    minimal section scan (our keys are flat ``name = <literal>`` lines,
    a subset shared by TOML and Python literal syntax)."""
    try:
        with open(pyproject_path, "rb") as fp:
            raw = fp.read()
    except OSError:
        return {}
    try:
        import tomllib  # Python 3.11+
    except ImportError:
        tomllib = None  # type: ignore[assignment]
    if tomllib is not None:
        try:
            data = tomllib.loads(raw.decode("utf-8"))
        except tomllib.TOMLDecodeError:
            # A broken pyproject must not crash the linter; the ruff/
            # mypy steps of the gate will report it far more legibly.
            return {}
        tool = data.get("tool", {}).get("graftlint", {})
        return dict(tool) if isinstance(tool, dict) else {}
    out: dict = {}
    in_section = False
    key: Optional[str] = None
    buffer = ""
    key_re = re.compile(r"^[A-Za-z0-9_-]+\s*=")
    for line in raw.decode("utf-8").splitlines():
        stripped = line.strip()
        if stripped.startswith("["):
            in_section = stripped == "[tool.graftlint]"
            key, buffer = None, ""
            continue
        if not in_section or not stripped or stripped.startswith("#"):
            continue
        if key is None or key_re.match(stripped):
            # A fresh `name = value` line also abandons any stuck
            # accumulation from an unparseable previous value.
            if "=" not in stripped:
                continue
            key, _, buffer = stripped.partition("=")
            key = key.strip()
        else:
            # A value (e.g. a list) may span lines; keep accumulating
            # until it parses as a literal.
            buffer += " " + stripped
        try:
            out[key] = ast.literal_eval(_toml_scalars(buffer.strip()))
            key, buffer = None, ""
        except (ValueError, SyntaxError):
            continue
    return out


def _toml_scalars(value: str) -> str:
    """Map bare TOML booleans onto Python literals for the fallback
    parser (the only TOML/Python-literal divergence our flat keys use)."""
    return {"true": "True", "false": "False"}.get(value, value)


def config_from_pyproject(pyproject_path: str) -> LintConfig:
    raw = load_pyproject_config(pyproject_path)
    cfg = LintConfig()
    if "disable" in raw:
        cfg.disable = {str(r) for r in raw["disable"]}
    if "exclude" in raw:
        cfg.exclude = _DEFAULT_EXCLUDES + tuple(str(g) for g in raw["exclude"])
    if "hot-path-dirs" in raw:
        cfg.hot_path_dirs = tuple(str(d) for d in raw["hot-path-dirs"])
    if "hot-path-files" in raw:
        cfg.hot_path_files = tuple(str(f) for f in raw["hot-path-files"])
    if "request-path-dirs" in raw:
        cfg.request_path_dirs = tuple(str(d) for d in raw["request-path-dirs"])
    if "concurrency-dirs" in raw:
        cfg.concurrency_dirs = tuple(str(d) for d in raw["concurrency-dirs"])
    return cfg


def parse_suppressions(lines: Sequence[str]) -> dict[int, set[str]]:
    """``# graftlint: disable=GL001[,GL004]`` suppresses those rules on
    its own line; ``disable-next-line=`` suppresses them on the line
    after (for statements whose trailing comment space is taken)."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        target = i + 1 if m.group(1) == "disable-next-line" else i
        ids = {part.strip() for part in m.group(2).split(",") if part.strip()}
        out.setdefault(target, set()).update(ids)
    return out


def _excluded(path: str, patterns: Iterable[str]) -> bool:
    name = os.path.basename(path)
    return any(
        fnmatch.fnmatch(name, pat) or fnmatch.fnmatch(path, pat)
        for pat in patterns
    )


def iter_python_files(
    paths: Sequence[str], exclude: Iterable[str] = (),
    root: Optional[str] = None,
) -> Iterator[str]:
    """Yield .py files under ``paths`` (files directly, dirs walked)."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not _excluded(_posix(p, root), exclude):
                yield p
            continue
        for base, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d not in ("__pycache__", ".git") and not _excluded(d, exclude)
            )
            for fname in sorted(files):
                full = os.path.join(base, fname)
                if fname.endswith(".py") and not _excluded(
                    _posix(full, root), exclude
                ):
                    yield full


def _posix(path: str, root: Optional[str] = None) -> str:
    """Repo-root-relative posix path: finding paths (and therefore
    baseline fingerprints) must not depend on the invocation CWD."""
    rel = os.path.relpath(path, root or os.getcwd())
    return rel.replace(os.sep, "/")


def _load_file(
    path: str, root: Optional[str] = None
) -> "tuple[FileContext, ast.Module] | Finding | None":
    """Read and parse one file: ``(ctx, tree)`` on success, a GL000
    :class:`Finding` on syntax error, ``None`` on I/O failure."""
    rel = _posix(path, root)
    try:
        with open(path, "r", encoding="utf-8") as fp:
            source = fp.read()
    except (OSError, UnicodeDecodeError):
        return None
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return Finding(
            rule_id="GL000",
            path=rel,
            line=exc.lineno or 1,
            col=(exc.offset or 1) - 1,
            message=f"syntax error: {exc.msg}",
            code_line="",
        )
    lines = source.splitlines()
    return (
        FileContext(
            path=rel,
            source=source,
            lines=lines,
            suppressions=parse_suppressions(lines),
            abs_path=os.path.abspath(path),
        ),
        tree,
    )


def _run_file_rules(
    tree: ast.Module,
    ctx: FileContext,
    rules: Sequence[Rule],
    config: LintConfig,
) -> list[Finding]:
    findings: list[Finding] = []
    for rule in rules:
        if not config.wants(rule.rule_id) or not rule.applies_to(ctx.path):
            continue
        for f in rule.check(tree, ctx):
            if not ctx.suppressed(f.rule_id, f.line):
                findings.append(f)
    return findings


def analyze_file(
    path: str, rules: Sequence[Rule], config: LintConfig,
    root: Optional[str] = None,
) -> list[Finding]:
    loaded = _load_file(path, root)
    if loaded is None:
        return []
    if isinstance(loaded, Finding):
        return [loaded]
    ctx, tree = loaded
    findings = _run_file_rules(tree, ctx, rules, config)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return findings


def run_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    config: Optional[LintConfig] = None,
    root: Optional[str] = None,
) -> list[Finding]:
    """Analyze every Python file under ``paths`` with ``rules``.

    Two-phase: per-file rules run as each file parses; once every file
    is in, :class:`ProjectRule`\\ s run against the
    :class:`~gofr_tpu.analysis.project.ProjectIndex` built from the
    whole parsed set (each file is parsed exactly once for both
    phases).

    ``root`` anchors the reported (and fingerprinted) paths; pass the
    repo root so baselines match regardless of the invocation CWD."""
    from gofr_tpu.analysis.rules import default_rules

    config = config or LintConfig()
    rules = list(rules) if rules is not None else default_rules(config)
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [
        r for r in rules
        if isinstance(r, ProjectRule) and config.wants(r.rule_id)
    ]
    out: list[Finding] = []
    parsed: list[tuple[FileContext, ast.Module]] = []
    for path in iter_python_files(paths, config.exclude, root):
        loaded = _load_file(path, root)
        if loaded is None:
            continue
        if isinstance(loaded, Finding):
            out.append(loaded)
            continue
        ctx, tree = loaded
        out.extend(_run_file_rules(tree, ctx, file_rules, config))
        parsed.append((ctx, tree))
    if project_rules and parsed:
        from gofr_tpu.analysis.project import ProjectIndex

        index = ProjectIndex.build(parsed)
        for rule in project_rules:
            for f in rule.check_project(index):
                if not rule.applies_to(f.path):
                    continue
                fctx = index.files.get(f.path)
                if fctx is not None and fctx.suppressed(f.rule_id, f.line):
                    continue
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return out


def build_index(
    paths: Sequence[str],
    config: Optional[LintConfig] = None,
    root: Optional[str] = None,
):
    """Parse every Python file under ``paths`` and build the
    :class:`~gofr_tpu.analysis.project.ProjectIndex` alone — no rules
    run. For consumers that want the static concurrency model without
    a lint pass (``/debug/lockgraph`` diffs it against the runtime
    lock-order graph). Returns ``None`` when nothing parsed."""
    from gofr_tpu.analysis.project import ProjectIndex

    config = config or LintConfig()
    parsed: list[tuple[FileContext, ast.Module]] = []
    for path in iter_python_files(paths, config.exclude, root):
        loaded = _load_file(path, root)
        if loaded is None or isinstance(loaded, Finding):
            continue
        parsed.append(loaded)
    return ProjectIndex.build(parsed) if parsed else None


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------


def fingerprint_findings(findings: Sequence[Finding]) -> dict[str, Finding]:
    """Fingerprint each finding, disambiguating identical lines by their
    occurrence order within (path, rule, content)."""
    counts: dict[tuple[str, str, str], int] = {}
    out: dict[str, Finding] = {}
    for f in findings:
        key = (f.path, f.rule_id, f.code_line)
        n = counts.get(key, 0)
        counts[key] = n + 1
        out[f.fingerprint(n)] = f
    return out


class Baseline:
    """The committed ledger of accepted pre-existing findings.

    A finding whose fingerprint is in the baseline is *known debt* and
    does not fail the run; a baseline entry with no live finding is
    *drift* (the debt was paid — ``--check-baseline`` demands the file
    be regenerated so it can never grow stale)."""

    VERSION = 1

    def __init__(self, entries: Optional[dict[str, dict]] = None) -> None:
        self.entries: dict[str, dict] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as fp:
                data = json.load(fp)
        except (OSError, ValueError):
            return cls()
        if not isinstance(data, dict) or data.get("version") != cls.VERSION:
            return cls()
        entries = data.get("findings", {})
        return cls(entries if isinstance(entries, dict) else {})

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        entries = {
            fp: {
                "rule": f.rule_id,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "code": f.code_line,
            }
            for fp, f in fingerprint_findings(findings).items()
        }
        return cls(entries)

    def write(self, path: str) -> None:
        payload = {
            "version": self.VERSION,
            "findings": {k: self.entries[k] for k in sorted(self.entries)},
        }
        with open(path, "w", encoding="utf-8") as fp:
            json.dump(payload, fp, indent=2, sort_keys=False)
            fp.write("\n")

    def apply(
        self,
        findings: Sequence[Finding],
        active_rules: Optional[set[str]] = None,
    ) -> tuple[list[Finding], list[str]]:
        """Split ``findings`` into (new, stale-fingerprints).

        ``active_rules`` limits staleness to entries of rules that
        actually ran — a ``--select GL001`` run produces no GL006
        findings, and that absence must not count as paid-off debt."""
        live = fingerprint_findings(findings)
        new = [f for fp, f in live.items() if fp not in self.entries]
        stale = [
            fp for fp, entry in self.entries.items()
            if fp not in live
            and (active_rules is None or entry.get("rule") in active_rules)
        ]
        new.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
        return new, sorted(stale)
