"""graftlint CLI — ``python -m gofr_tpu.analysis``.

Exit codes: 0 clean (relative to the baseline), 1 findings or baseline
drift, 2 usage error. ``--write-baseline`` records the current findings
as accepted debt; ``--check-baseline`` additionally fails when the
baseline holds entries that no longer occur (paid-off debt must be
removed from the ledger so it can never mask a regression on the same
line).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from gofr_tpu.analysis.core import (
    Baseline,
    Finding,
    Rule,
    config_from_pyproject,
    run_paths,
)
from gofr_tpu.analysis.rules import default_rules

DEFAULT_BASELINE = "graftlint-baseline.json"

#: SARIF 2.1.0 — the minimal subset GitHub code scanning ingests.
_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _sarif_report(
    findings: Sequence[Finding], rules: Sequence[Rule]
) -> dict[str, object]:
    """One-run SARIF log: every registered rule in the driver (so the
    upload shows rule metadata even for clean runs), one result per
    finding. Paths are repo-relative already — they become artifact
    URIs verbatim."""
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [
                            {
                                "id": r.rule_id,
                                "name": r.name,
                                "shortDescription": {"text": r.rationale},
                            }
                            for r in rules
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule_id,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": f.path.replace(os.sep, "/"),
                                    },
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def _find_repo_root(start: str) -> str:
    """Nearest ancestor holding pyproject.toml (config + baseline home)."""
    cur = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m gofr_tpu.analysis",
        description="graftlint: TPU-correctness static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", default=["gofr_tpu"],
        help="files or directories to analyze (default: gofr_tpu)",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--ignore", default="",
        help="comma-separated rule ids to skip",
    )
    parser.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <repo root>/{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="record current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--check-baseline", action="store_true",
        help="also fail when baseline entries no longer occur (drift)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (sarif: SARIF 2.1.0, for code-scanning upload)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rules and exit",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    root = _find_repo_root(
        args.paths[0] if args.paths and os.path.exists(args.paths[0]) else "."
    )
    config = config_from_pyproject(os.path.join(root, "pyproject.toml"))
    if args.select:
        config.select = {r.strip() for r in args.select.split(",") if r.strip()}
    if args.ignore:
        config.disable |= {
            r.strip() for r in args.ignore.split(",") if r.strip()
        }

    rules = default_rules(config)
    if args.list_rules:
        for rule in rules:
            print(f"{rule.rule_id}  {rule.name}: {rule.rationale}")
        return 0

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = run_paths(args.paths, rules=rules, config=config, root=root)
    active_ids = {r.rule_id for r in rules if config.wants(r.rule_id)}
    scoped = bool(args.select or args.ignore)

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        new_baseline = Baseline.from_findings(findings)
        if scoped:
            # A scoped run never saw the other rules' findings; keep
            # their recorded debt instead of silently deleting it.
            old = Baseline.load(baseline_path)
            for fp, entry in old.entries.items():
                if entry.get("rule") not in active_ids:
                    new_baseline.entries[fp] = entry
        new_baseline.write(baseline_path)
        print(
            f"graftlint: wrote {len(new_baseline.entries)} finding(s) "
            f"to {baseline_path}"
        )
        return 0

    stale: list[str] = []
    if args.no_baseline:
        new = findings
    else:
        baseline = Baseline.load(baseline_path)
        new, stale = baseline.apply(
            findings, active_rules=active_ids if scoped else None
        )

    if args.format == "sarif":
        print(json.dumps(_sarif_report(new, rules), indent=2))
    elif args.format == "json":
        print(json.dumps(
            {
                "findings": [
                    {
                        "rule": f.rule_id, "path": f.path, "line": f.line,
                        "col": f.col + 1, "message": f.message,
                    }
                    for f in new
                ],
                "stale_baseline_entries": stale if args.check_baseline else [],
            },
            indent=2,
        ))
    else:
        for f in new:
            print(f.render())

    failed = bool(new)
    if args.check_baseline and stale:
        failed = True
        if args.format == "text":
            print(
                f"graftlint: {len(stale)} baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} no longer occur — "
                "regenerate with --write-baseline",
                file=sys.stderr,
            )
    if failed and args.format == "text" and new:
        print(
            f"graftlint: {len(new)} new finding(s) "
            "(suppress in place with `# graftlint: disable=RULE` "
            "or accept with --write-baseline)",
            file=sys.stderr,
        )
    return 1 if failed else 0
