"""``python -m gofr_tpu.analysis`` entrypoint."""

import sys

from gofr_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
