"""The graftlint rule set (GL001–GL025).

Each rule encodes one class of TPU-serving bug that generic linters
cannot see because it is a *semantic* property of the jax programming
model, not a syntax smell. The heuristics are deliberately conservative:
a rule should only fire where a human reviewer would at least pause —
anything intentional gets an inline ``# graftlint: disable=RULE`` with
its justification, which doubles as documentation at the call site.

GL001–GL019 and GL023–GL025 are per-file :class:`Rule`\\ s;
GL020–GL022 are :class:`ProjectRule`\\ s running against the cross-file
:class:`~gofr_tpu.analysis.project.ProjectIndex` (call graph, lock
model, thread roots) built by the two-phase runner.
"""

from __future__ import annotations

import ast
from dataclasses import replace
from typing import Iterator, Optional, Sequence

from gofr_tpu.analysis.core import (
    FileContext,
    Finding,
    LintConfig,
    ProjectRule,
    Rule,
)
from gofr_tpu.analysis.project import AttrAccess, ProjectIndex, lock_regions

# ----------------------------------------------------------------------
# shared AST helpers
# ----------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)``/``pjit(...)``/``partial(jax.jit, ...)`` Call
    carrying static-arg kwargs, if ``node`` is a jit wrapper expression."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func) or ""
    short = name.rsplit(".", 1)[-1]
    if short in ("jit", "pjit"):
        return node
    if short == "partial" and node.args:
        inner = dotted_name(node.args[0]) or ""
        if inner.rsplit(".", 1)[-1] in ("jit", "pjit"):
            return node
    return None


def is_jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dotted_name(dec) or ""
        if name.rsplit(".", 1)[-1] in ("jit", "pjit"):
            return True
        if _jit_call(dec) is not None:
            return True
    return False


def jit_static_names(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> set[str]:
    """Parameter names declared static via static_argnums/static_argnames
    on the function's jit decorator (constant specs only)."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: set[str] = set()
    for dec in fn.decorator_list:
        call = _jit_call(dec)
        if call is None:
            continue
        for kw in call.keywords:
            value = _const_value(kw.value)
            if kw.arg == "static_argnums" and value is not None:
                nums = value if isinstance(value, (tuple, list)) else (value,)
                for n in nums:
                    if isinstance(n, int) and 0 <= n < len(params):
                        static.add(params[n])
            elif kw.arg == "static_argnames" and value is not None:
                names = value if isinstance(value, (tuple, list)) else (value,)
                static.update(str(n) for n in names)
    return static


def _const_value(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _contains_shape_attr(node: ast.AST) -> bool:
    return any(
        isinstance(sub, ast.Attribute) and sub.attr == "shape"
        for sub in ast.walk(node)
    )


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


# ----------------------------------------------------------------------
# GL001 — host↔device sync on the hot path
# ----------------------------------------------------------------------


class HostDeviceSyncRule(Rule):
    """``.item()`` / ``float()`` / ``int()`` / ``np.asarray()`` on a
    device array forces a blocking device→host transfer. On the decode
    hot path one stray sync serializes the pipelined windows and costs a
    full host↔device RTT (~66 ms on a network-attached relay) per call.

    Device values are recognized by this codebase's ``*_dev`` naming
    convention (the engine's device-resident planes) plus names assigned
    from ``jnp.*``/``jax.device_put`` expressions in the same scope.
    """

    rule_id = "GL001"
    name = "host-device-sync"
    rationale = (
        "blocking device→host syncs on the dispatch path serialize the "
        "window pipeline; fetch asynchronously or keep the value on device"
    )

    def __init__(self, hot_path_dirs: Sequence[str] = ("serving", "ops")) -> None:
        self._dirs = tuple(hot_path_dirs)

    def applies_to(self, path: str) -> bool:
        return any(f"/{d}/" in f"/{path}" for d in self._dirs)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        device_names = self._infer_device_names(tree)

        def is_device(node: ast.AST) -> bool:
            while isinstance(node, ast.Subscript):
                node = node.value
            name = dotted_name(node)
            if name is None:
                return False
            leaf = name.rsplit(".", 1)[-1]
            return leaf.endswith("_dev") or leaf in device_names

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            # x.item() — always a sync.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                yield self.finding(
                    ctx, node,
                    "`.item()` blocks on a device→host transfer; fetch via "
                    "an async copy (`copy_to_host_async`) or batch the read",
                )
                continue
            fname = dotted_name(node.func) or ""
            leaf = fname.rsplit(".", 1)[-1]
            if not node.args:
                continue
            arg = node.args[0]
            if fname in ("float", "int", "bool") and is_device(arg):
                yield self.finding(
                    ctx, node,
                    f"`{fname}()` on a device array is a blocking "
                    "device→host sync on the hot path",
                )
            elif leaf in ("asarray", "array") and fname.split(".")[0] in (
                "np", "numpy", "onp"
            ) and is_device(arg):
                yield self.finding(
                    ctx, node,
                    f"`{fname}()` on a device array blocks until the "
                    "transfer completes; overlap it with "
                    "`copy_to_host_async` + `is_ready` instead",
                )

    @staticmethod
    def _infer_device_names(tree: ast.Module) -> set[str]:
        """Names assigned from obviously-device-producing expressions."""
        out: set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            src = dotted_name(node.value.func) or ""
            root, leaf = src.split(".")[0], src.rsplit(".", 1)[-1]
            if root in ("jnp", "jax") or leaf in ("device_put",):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out


# ----------------------------------------------------------------------
# GL002 — Python branching on tracer values inside jit
# ----------------------------------------------------------------------


class TracerBranchRule(Rule):
    """Inside a ``@jax.jit`` function the array arguments are tracers:
    ``if x > 0:`` raises ``TracerBoolConversionError`` at trace time (or
    silently bakes one branch in if the value is concrete on the first
    call). Data-dependent control flow belongs in ``lax.cond`` /
    ``lax.while_loop`` / ``jnp.where``.

    Shape/dtype reads (``x.shape``, ``x.ndim``, ``len(x)``) are static
    under tracing and never flagged; parameters named in
    ``static_argnums``/``static_argnames`` are exempt.
    """

    rule_id = "GL002"
    name = "tracer-branch"
    rationale = (
        "Python `if`/`while` on a traced value either crashes at trace "
        "time or freezes one branch into the compiled program"
    )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and is_jit_decorated(node):
                yield from self._check_fn(node, ctx)

    def _check_fn(
        self, fn: ast.FunctionDef | ast.AsyncFunctionDef, ctx: FileContext
    ) -> Iterator[Finding]:
        static = jit_static_names(fn)
        tainted = {
            a.arg
            for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
            if a.arg not in static and a.arg not in ("self", "cls")
        }
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign):
                # One-pass taint propagation through simple assignments.
                if self._expr_tainted(stmt.value, tainted):
                    for tgt in stmt.targets:
                        for name in ast.walk(tgt):
                            if isinstance(name, ast.Name):
                                tainted.add(name.id)
            elif isinstance(stmt, (ast.If, ast.While)):
                if self._expr_tainted(stmt.test, tainted):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    yield self.finding(
                        ctx, stmt.test,
                        f"Python `{kind}` on a traced value inside "
                        f"`{fn.name}` (jitted); use `lax.cond`/"
                        "`lax.while_loop`/`jnp.where`, or declare the "
                        "argument static",
                    )

    def _expr_tainted(self, expr: ast.AST, tainted: set[str]) -> bool:
        """Does ``expr``'s *runtime value* depend on a tracer?

        Attribute reads of static metadata (``.shape``, ``.dtype``, …)
        and ``len()``/``isinstance()`` calls launder the taint — they
        are Python-level constants under tracing."""
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
        ):
            # `x is None` / `x is not None` are Python identity checks —
            # resolved at trace time, never a tracer bool.
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self._expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func) or ""
            if name in ("len", "isinstance", "getattr", "hasattr", "type"):
                return False
            parts: list[ast.AST] = list(expr.args) + [
                kw.value for kw in expr.keywords
            ]
            if isinstance(expr.func, ast.Attribute):
                # x.sum() on a tracer yields a tracer.
                parts.append(expr.func.value)
            return any(self._expr_tainted(p, tainted) for p in parts)
        return any(
            self._expr_tainted(child, tainted)
            for child in ast.iter_child_nodes(expr)
        )


# ----------------------------------------------------------------------
# GL003 — recompilation hazards
# ----------------------------------------------------------------------


class RecompilationHazardRule(Rule):
    """Every distinct static-arg value (and every unhashable one) is a
    fresh XLA compile; on TPU a recompile is seconds of wall clock in
    the serving path. Flags:

    * mutable literals (list/dict/set) passed in a static position of a
      module-local ``jax.jit(fn, static_arg...)`` wrapper — unhashable,
      crashes at call time;
    * dict/cache keys or subscripts built from ``.shape`` f-strings —
      the signature of a hand-rolled compile cache keyed on shapes,
      which grows without bound under bucketed padding drift.
    """

    rule_id = "GL003"
    name = "recompilation-hazard"
    rationale = (
        "unhashable/mutable static args fail or recompile per call; "
        "shape-keyed caches churn compiles under padding drift"
    )

    _MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp, ast.GeneratorExp)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        jitted = self._collect_jit_wrappers(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(node, jitted, ctx)
                continue
            # d[f"{x.shape}"] / {x.shape: ...} — shape-keyed cache.
            if isinstance(node, ast.Subscript) and self._shape_key(node.slice):
                yield self.finding(
                    ctx, node,
                    "subscript keyed on a `.shape`-derived value: a "
                    "hand-rolled compile cache keyed on shapes recompiles "
                    "per padding bucket; key on the bucket id instead",
                )
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if key is not None and self._shape_key(key):
                        yield self.finding(
                            ctx, key,
                            "dict key built from `.shape`: shape-keyed "
                            "caches churn compiles; key on the padded "
                            "bucket instead",
                        )

    def _check_call(
        self,
        node: ast.Call,
        jitted: dict[str, tuple[set[int], set[str]]],
        ctx: FileContext,
    ) -> Iterator[Finding]:
        name = dotted_name(node.func)
        if name is None or name not in jitted:
            return
        static_nums, static_names = jitted[name]
        for i, arg in enumerate(node.args):
            if i in static_nums and isinstance(arg, self._MUTABLE):
                yield self.finding(
                    ctx, arg,
                    f"mutable literal passed as static arg {i} of jitted "
                    f"`{name}`: unhashable static args raise at call "
                    "time — pass a tuple or mark the arg non-static",
                )
        for kw in node.keywords:
            if kw.arg in static_names and isinstance(kw.value, self._MUTABLE):
                yield self.finding(
                    ctx, kw.value,
                    f"mutable literal passed as static kwarg "
                    f"`{kw.arg}` of jitted `{name}`",
                )

    @staticmethod
    def _shape_key(node: ast.AST) -> bool:
        if isinstance(node, ast.JoinedStr):
            return any(
                _contains_shape_attr(v.value)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            )
        return isinstance(node, ast.Attribute) and node.attr == "shape"

    @staticmethod
    def _collect_jit_wrappers(
        tree: ast.Module,
    ) -> dict[str, tuple[set[int], set[str]]]:
        """``g = jax.jit(f, static_argnums=(1,))`` → {"g": ({1}, set())}."""
        out: dict[str, tuple[set[int], set[str]]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            call = _jit_call(node.value)
            if call is None:
                continue
            nums: set[int] = set()
            names: set[str] = set()
            for kw in call.keywords:
                value = _const_value(kw.value)
                if value is None:
                    continue
                seq = value if isinstance(value, (tuple, list)) else (value,)
                if kw.arg == "static_argnums":
                    nums.update(int(v) for v in seq if isinstance(v, int))
                elif kw.arg == "static_argnames":
                    names.update(str(v) for v in seq)
            if not nums and not names:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = (nums, names)
        return out


# ----------------------------------------------------------------------
# GL004 — blocking calls in async / hot-path code
# ----------------------------------------------------------------------


class BlockingCallRule(Rule):
    """``time.sleep`` (and friends) inside an ``async def`` stalls the
    whole event loop; inside the batcher/scheduler/engine hot path it
    turns an event wait into a latency floor — a 50 ms poll loop is
    50 ms of p50 added to every drain. Waits belong on
    ``threading.Event``/``Condition`` (or ``asyncio.sleep`` in async
    code) where a state change wakes the waiter immediately.
    """

    rule_id = "GL004"
    name = "blocking-call"
    rationale = (
        "blocking sleeps/IO stall the event loop or add poll-interval "
        "latency to the batch hot path; wait on events/conditions"
    )

    _BLOCKING = {
        "time.sleep": "blocks the thread",
        "os.system": "synchronous subprocess",
        "subprocess.run": "synchronous subprocess",
        "subprocess.call": "synchronous subprocess",
        "subprocess.check_call": "synchronous subprocess",
        "subprocess.check_output": "synchronous subprocess",
        "subprocess.Popen": "spawns a process (fork latency)",
        "requests.get": "synchronous HTTP",
        "requests.post": "synchronous HTTP",
        "urllib.request.urlopen": "synchronous HTTP",
        "socket.create_connection": "synchronous connect",
    }

    def __init__(
        self,
        hot_path_files: Sequence[str] = (
            "serving/batcher.py",
            "serving/scheduler.py",
            "serving/engine.py",
        ),
    ) -> None:
        self._hot_files = tuple(hot_path_files)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        hot_file = any(ctx.path.endswith(f) for f in self._hot_files)
        # Collect the line spans of async defs so sync helpers nested in
        # them are covered too.
        async_spans = [
            (n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, ast.AsyncFunctionDef)
        ]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            desc = self._BLOCKING.get(name)
            if desc is None:
                continue
            in_async = any(
                lo <= node.lineno <= hi for lo, hi in async_spans
            )
            if in_async:
                yield self.finding(
                    ctx, node,
                    f"`{name}` ({desc}) inside an `async def` stalls the "
                    "event loop; use the asyncio equivalent or "
                    "`run_in_executor`",
                )
            elif hot_file and name == "time.sleep":
                yield self.finding(
                    ctx, node,
                    "`time.sleep` on the batcher/scheduler hot path adds "
                    "its full poll interval to tail latency; wait on a "
                    "`threading.Event`/`Condition` instead",
                )


# ----------------------------------------------------------------------
# GL005 — lock discipline over shared mutable state
# ----------------------------------------------------------------------


class LockDisciplineRule(Rule):
    """If a class protects an attribute with a lock *somewhere*, every
    write to that attribute outside ``__init__`` must hold the lock —
    mixed discipline is how torn reads ship. Attributes written at least
    once inside ``with self.<lock>:`` are 'guarded'; any other write to
    them outside a with-lock block is flagged. (The race-detector-CI
    spirit of the reference framework, approximated statically.)

    The hot-path files compose ONE runtime object (mixins over
    ``InferenceEngine``), so guarded-attribute knowledge is unioned
    across all of them — a write in ``scheduler.py`` is checked against
    locks taken in ``engine.py`` and vice versa; a per-class analysis
    would be blind across exactly the seam it was written for.
    """

    rule_id = "GL005"
    name = "lock-discipline"
    rationale = (
        "an attribute written both under and outside a lock has no "
        "consistent happens-before edge; hold the lock everywhere"
    )

    def __init__(
        self,
        hot_path_files: Sequence[str] = (
            "serving/batcher.py",
            "serving/scheduler.py",
            "serving/engine.py",
        ),
    ) -> None:
        self._hot_files = tuple(hot_path_files)
        self._sibling_guarded: dict[str, set[str]] = {}

    def applies_to(self, path: str) -> bool:
        return any(path.endswith(f) for f in self._hot_files)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        composed = self._composed_guarded(tree, ctx)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, ctx, composed)

    def _composed_guarded(
        self, tree: ast.Module, ctx: FileContext
    ) -> set[str]:
        """Locked-write attributes across the whole composed object:
        every class in this file plus every class in the sibling
        hot-path files (parsed once per run)."""
        guarded: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                guarded |= self._class_writes(node)[0]
        abs_path = ctx.abs_path
        suffix = next(
            (f for f in self._hot_files if ctx.path.endswith(f)), None
        )
        if abs_path and suffix and abs_path.endswith(suffix):
            base = abs_path[: -len(suffix)]
            for sib in self._hot_files:
                if sib == suffix:
                    continue
                sib_path = base + sib
                if sib_path not in self._sibling_guarded:
                    self._sibling_guarded[sib_path] = (
                        self._guarded_in_file(sib_path)
                    )
                guarded |= self._sibling_guarded[sib_path]
        return guarded

    def _guarded_in_file(self, path: str) -> set[str]:
        try:
            with open(path, "r", encoding="utf-8") as fp:
                tree = ast.parse(fp.read())
        except (OSError, SyntaxError, UnicodeDecodeError):
            return set()
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                out |= self._class_writes(node)[0]
        return out

    def _class_writes(
        self, cls: ast.ClassDef
    ) -> tuple[set[str], list[tuple[str, ast.AST]]]:
        """(locked-write attrs, unlocked writes) for one class body,
        skipping ``__init__`` (construction precedes sharing)."""
        guarded: set[str] = set()
        unlocked: list[tuple[str, ast.AST]] = []
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            # Shared with the GL020 index path: lock_regions() subtracts
            # manual release()/acquire() windows, so a write in the
            # except/finally of a released window counts as UNLOCKED —
            # the lexical with-span alone used to mis-classify exactly
            # that shape as guarded. Nested defs keep their own regions
            # (lock_regions stops at scope boundaries, so union them).
            regions = list(lock_regions(method))
            for sub in ast.walk(method):
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and sub is not method:
                    regions.extend(lock_regions(sub))
            for stmt in ast.walk(method):
                attr = self._self_attr_write(stmt)
                if attr is None:
                    continue
                line = stmt.lineno
                if any(r.holds_at(line) for r in regions):
                    guarded.add(attr)
                else:
                    unlocked.append((attr, stmt))
        return guarded, unlocked

    def _check_class(
        self, cls: ast.ClassDef, ctx: FileContext, composed: set[str]
    ) -> Iterator[Finding]:
        _, unlocked = self._class_writes(cls)
        for attr, stmt in unlocked:
            if attr in composed:
                yield self.finding(
                    ctx, stmt,
                    f"`self.{attr}` is written under a lock elsewhere in "
                    "the composed serving core but not here; hold the "
                    "same lock (or document why this write cannot race)",
                )

    @staticmethod
    def _self_attr_write(stmt: ast.AST) -> Optional[str]:
        """`self.x = ...` / `self.x += ...` (plain flags, not containers:
        `self._slots[i] = ...` mutates through a reference the scheduler
        thread owns — a different discipline, out of scope here)."""
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                return tgt.attr
        return None


# ----------------------------------------------------------------------
# GL006 — swallowed exceptions in request paths
# ----------------------------------------------------------------------


class ExceptionSwallowRule(Rule):
    """A bare/overbroad `except` that neither logs, re-raises, nor
    records the error swallows jax's rich failure modes
    (``XlaRuntimeError``, OOM, donation errors) exactly where the caller
    most needs them — a request silently returns garbage instead of a
    500. Handlers that log, raise, or set an exception on a future are
    fine; ``pass``-bodies must narrow the exception type or carry a
    suppression with their justification.
    """

    rule_id = "GL006"
    name = "swallowed-exception"
    rationale = (
        "broad except+pass hides XlaRuntimeError/OOM from request "
        "callers; narrow the type, log, or re-raise"
    )

    _BROAD = {"Exception", "BaseException"}

    def __init__(
        self, request_path_dirs: Sequence[str] = ("serving", "ops", "grpc")
    ) -> None:
        self._dirs = tuple(request_path_dirs)

    def applies_to(self, path: str) -> bool:
        return any(f"/{d}/" in f"/{path}" for d in self._dirs)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too; name the exception type",
                )
                continue
            if not self._is_broad(node.type):
                continue
            if not self._swallows(node):
                continue
            yield self.finding(
                ctx, node,
                f"broad `except {ast.unparse(node.type)}` whose body "
                "neither logs, re-raises, nor records the error would "
                "swallow jax runtime failures in the request path",
            )

    def _is_broad(self, type_node: ast.AST) -> bool:
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [dotted_name(e) or "" for e in type_node.elts]
        else:
            names = [dotted_name(type_node) or ""]
        return any(n.rsplit(".", 1)[-1] in self._BROAD for n in names)

    @staticmethod
    def _swallows(handler: ast.ExceptHandler) -> bool:
        """True when the body is a pure no-op (pass/continue/break, a
        constant expression, or a bare/constant return) — a handler that
        assigns a fallback, logs, raises, or records the error is
        *handling*, not swallowing."""
        for stmt in handler.body:
            if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant
            ):
                continue
            if isinstance(stmt, ast.Return) and (
                stmt.value is None or isinstance(stmt.value, ast.Constant)
            ):
                continue
            return False
        return True


# ----------------------------------------------------------------------
# GL007 — donated-buffer reuse after donate_argnums
# ----------------------------------------------------------------------


class DonatedBufferReuseRule(Rule):
    """``donate_argnums`` tells XLA it may overwrite the argument's
    buffer in place — after the call, the donated array is INVALID.
    Reading it again returns a "buffer has been deleted or donated"
    error at best and silent garbage through an aliased view at worst.
    The idiomatic pattern rebinds the result to the donated name
    (``cache = step(cache, ...)``); this rule flags reads of a donated
    name after a call that did NOT rebind it.

    Recognized donors: module/class-level ``g = jax.jit(f,
    donate_argnums=...)`` wrappers (including ``self.attr`` targets)
    and immediately-invoked ``jax.jit(f, donate_argnums=...)(x)``.
    Reassigning the name between the call and the read clears the
    taint.
    """

    rule_id = "GL007"
    name = "donated-buffer-reuse"
    rationale = (
        "donate_argnums invalidates the argument's buffer at the call; "
        "reading it afterwards crashes or returns garbage — rebind the "
        "result to the donated name"
    )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        donors = self._collect_donating_wrappers(tree)
        for scope in self._scopes(tree):
            yield from self._check_scope(scope, donors, ctx)

    @staticmethod
    def _scopes(tree: ast.Module) -> Iterator[ast.AST]:
        yield tree  # module body is a scope too
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _donate_nums(call: ast.Call) -> set[int]:
        """donate_argnums of a jit Call (constant specs only)."""
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                value = _const_value(kw.value)
                if value is None:
                    return set()
                seq = value if isinstance(value, (tuple, list)) else (value,)
                return {int(v) for v in seq if isinstance(v, int)}
        return set()

    def _collect_donating_wrappers(
        self, tree: ast.Module
    ) -> dict[str, set[int]]:
        """``g = jax.jit(f, donate_argnums=(0,))`` → {"g": {0}} (also
        ``self._step = ...`` attribute targets)."""
        out: dict[str, set[int]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            call = _jit_call(node.value)
            if call is None:
                continue
            nums = self._donate_nums(call)
            if not nums:
                continue
            for tgt in node.targets:
                name = dotted_name(tgt)
                if name is not None:
                    out[name] = nums
        return out

    def _check_scope(
        self,
        scope: ast.AST,
        donors: dict[str, set[int]],
        ctx: FileContext,
    ) -> Iterator[Finding]:
        # One recursive pass over the scope (NOT descending into nested
        # function/class bodies — separate scopes, separate lifetimes),
        # carrying the enclosing assignment's targets so `x = g(x)`
        # counts as a rebind, not a reuse.
        donations: list[tuple[str, int, int]] = []  # (name, line, col)
        assigns: dict[str, list[int]] = {}
        loads: list[tuple[str, ast.AST]] = []
        # Reads lexically inside a donating call evaluate BEFORE the
        # donation happens — never flag them.
        pre_call: set[int] = set()

        def visit(node: ast.AST, targets: list[str]) -> None:
            if isinstance(node, ast.Assign):
                names = [
                    n
                    for tgt in node.targets
                    for sub in ast.walk(tgt)
                    for n in [dotted_name(sub)]
                    if n is not None
                ]
                for n in names:
                    assigns.setdefault(n, []).append(node.lineno)
                targets = names
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                n = dotted_name(node.target)
                if n is not None:
                    assigns.setdefault(n, []).append(node.lineno)
                    targets = [n]
            if isinstance(node, ast.Call):
                nums: set[int] = set()
                fname = dotted_name(node.func)
                if fname is not None and fname in donors:
                    nums = donors[fname]
                elif isinstance(node.func, ast.Call):
                    jit = _jit_call(node.func)
                    if jit is not None:
                        nums = self._donate_nums(jit)
                if nums:
                    for sub in ast.walk(node):
                        pre_call.add(id(sub))
                for i in nums:
                    if i < len(node.args):
                        arg = node.args[i]
                        donated = dotted_name(arg)
                        if donated is not None and donated not in targets:
                            donations.append(
                                (donated, node.lineno, node.col_offset)
                            )
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                name = dotted_name(node)
                if name is not None:
                    loads.append((name, node))
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue
                visit(child, targets)

        for child in ast.iter_child_nodes(scope):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            visit(child, [])

        for name, node in loads:
            if id(node) in pre_call:  # evaluated before the donation
                continue
            for donated, call_line, call_col in donations:
                if name != donated:
                    continue
                if (node.lineno, node.col_offset) < (call_line, call_col):
                    continue
                # A reassignment between donation and read clears it.
                if any(
                    call_line < a <= node.lineno
                    for a in assigns.get(name, ())
                ):
                    continue
                yield self.finding(
                    ctx, node,
                    f"`{name}` was donated to a jitted call on line "
                    f"{call_line} (donate_argnums) — its buffer is gone; "
                    "rebind the call's result to it or drop the donation",
                )
                break


# ----------------------------------------------------------------------
# GL008 — jnp.asarray / jnp.array inside lax.scan bodies
# ----------------------------------------------------------------------


class ScanBodyAsarrayRule(Rule):
    """``jnp.asarray`` / ``jnp.array`` inside a ``jax.lax.scan`` body
    materializes its operand as a fresh constant (or convert op) in the
    LOOP BODY: the tracer runs the body once, but the embedded constant
    is baked per-compile and host data re-converts inside the hottest
    region of the program — on TPU a large baked constant bloats the
    executable and a per-iteration convert defeats the reason the layer
    stack was scanned in the first place. Hoist the conversion out of
    the body (close over a device array, or thread it through the scan
    carry/xs).

    Recognized bodies: a named function or lambda passed as the first
    argument (or ``f=`` keyword) of ``lax.scan`` / ``jax.lax.scan``.
    Factory calls (``scan(make_body(...), ...)``) are out of reach
    statically and deliberately skipped — conservative by design.
    """

    rule_id = "GL008"
    name = "scan-body-asarray"
    rationale = (
        "jnp.asarray/jnp.array in a lax.scan body bakes a constant or "
        "re-converts host data inside the scanned region; hoist it out "
        "of the body"
    )

    _CONVERTERS = {
        "jnp.asarray", "jnp.array", "jax.numpy.asarray", "jax.numpy.array",
    }

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        defs: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        seen: set[int] = set()  # one body scanned twice reports once
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            if fname not in ("lax.scan", "jax.lax.scan"):
                continue
            body_expr: Optional[ast.AST] = None
            if node.args:
                body_expr = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "f":
                        body_expr = kw.value
                        break
            body: Optional[ast.AST] = None
            if isinstance(body_expr, ast.Lambda):
                body = body_expr
            elif isinstance(body_expr, ast.Name):
                body = defs.get(body_expr.id)
            if body is None or id(body) in seen:
                continue
            seen.add(id(body))
            yield from self._check_body(body, ctx)

    def _check_body(self, body: ast.AST, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func) or ""
            if fname in self._CONVERTERS:
                yield self.finding(
                    ctx, node,
                    f"`{fname}` inside a `lax.scan` body bakes a "
                    "constant / re-converts host data in the scanned "
                    "region; hoist it out of the body (close over a "
                    "device array or thread it through the carry)",
                )


# ----------------------------------------------------------------------
# GL009 — per-request jit-cache growth
# ----------------------------------------------------------------------


class JitCacheGrowthRule(Rule):
    """A hand-rolled compile cache keyed on per-request values grows
    without bound: every distinct prompt length / shape / tensor size
    adds ANOTHER compiled executable that is never evicted, and on TPU
    each entry is seconds of compile time plus resident program memory.
    Two signatures are flagged:

    * ``functools.lru_cache`` / ``functools.cache`` on a callable whose
      body builds a jitted program, when the cache key can grow per
      request — an unbounded decorator (``cache`` or
      ``lru_cache(maxsize=None)``), a shape/length-named parameter, or
      a method (``self`` in the key also pins every engine instance
      alive);
    * dict-cached jit builders — ``cache[seq_len] = jax.jit(...)``
      (or ``.setdefault``) where the key is a shape/length-derived
      value.

    The fix is the codebase's bucketed-padding idiom: compile one
    fixed-shape program per PADDING BUCKET (a small closed set) and pad
    requests into it, instead of one program per observed request
    shape. GL003 flags ``.shape``-f-string keys; this rule catches the
    lru_cache/method and bare length-key forms it cannot see.
    """

    rule_id = "GL009"
    name = "jit-cache-growth"
    rationale = (
        "shape-keyed lru_cache/dict caches of jitted programs compile "
        "and retain one executable per observed request shape; key on a "
        "closed set of padding buckets instead"
    )

    _SHAPE_HINTS = ("shape", "len", "length", "size", "tokens", "dim")

    @classmethod
    def _shapeish(cls, name: str) -> bool:
        lowered = name.lower()
        return any(hint in lowered for hint in cls._SHAPE_HINTS)

    @staticmethod
    def _cache_decorator(dec: ast.AST) -> Optional[tuple[str, bool]]:
        """(decorator name, unbounded?) for lru_cache/cache decorators."""
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call is not None else dec
        name = dotted_name(target) or ""
        short = name.rsplit(".", 1)[-1]
        if short == "cache":
            return name, True
        if short != "lru_cache":
            return None
        if call is None:
            return name, False  # bare @lru_cache: default maxsize=128
        for kw in call.keywords:
            if kw.arg == "maxsize":
                value = _const_value(kw.value)
                return name, value is None
        if call.args:
            return name, _const_value(call.args[0]) is None
        return name, False

    @staticmethod
    def _builds_jit(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _jit_call(node) is not None:
                return True
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_cached_fn(node, ctx)
            elif isinstance(node, ast.Assign):
                yield from self._check_dict_cache(
                    node.targets, node.value, ctx
                )
            elif isinstance(node, ast.Call):
                yield from self._check_setdefault(node, ctx)

    def _check_cached_fn(
        self, fn: ast.FunctionDef | ast.AsyncFunctionDef, ctx: FileContext
    ) -> Iterator[Finding]:
        cached = None
        for dec in fn.decorator_list:
            cached = self._cache_decorator(dec)
            if cached is not None:
                break
        if cached is None or not self._builds_jit(fn):
            return
        dec_name, unbounded = cached
        params = [
            a.arg for a in fn.args.posonlyargs + fn.args.args
            + fn.args.kwonlyargs
        ]
        is_method = bool(params) and params[0] in ("self", "cls")
        shape_params = [p for p in params if self._shapeish(p)]
        if not (unbounded or is_method or shape_params):
            return  # bounded cache over a closed key set: the fix itself
        if unbounded:
            why = f"`@{dec_name}` is unbounded"
        elif is_method:
            why = (
                f"`@{dec_name}` on a method keys on `{params[0]}` too — "
                "the cache pins every instance AND grows per shape"
            )
        else:
            why = (
                f"key includes per-request value(s) "
                f"{', '.join(repr(p) for p in shape_params)}"
            )
        yield self.finding(
            ctx, fn,
            f"`{fn.name}` builds a jitted program under `@{dec_name}` "
            f"and {why}: the compile cache grows per request — key on a "
            "closed set of padding buckets (bounded maxsize, "
            "module-level function)",
        )

    def _check_dict_cache(
        self, targets: list[ast.AST], value: ast.AST, ctx: FileContext
    ) -> Iterator[Finding]:
        if not isinstance(value, ast.Call) or _jit_call(value) is None:
            return
        for tgt in targets:
            if isinstance(tgt, ast.Subscript) and self._growing_key(
                tgt.slice
            ):
                yield self.finding(
                    ctx, tgt,
                    "jitted program stored under a shape/length-derived "
                    "dict key: the cache compiles and retains one "
                    "executable per observed request shape; key on a "
                    "padding bucket instead",
                )

    def _check_setdefault(
        self, node: ast.Call, ctx: FileContext
    ) -> Iterator[Finding]:
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "setdefault"
            and len(node.args) >= 2
        ):
            return
        if _jit_call(node.args[1]) is not None and self._growing_key(
            node.args[0]
        ):
            yield self.finding(
                ctx, node,
                "jitted program `setdefault`-cached under a shape/"
                "length-derived key grows the compile cache per request; "
                "key on a padding bucket instead",
            )

    def _growing_key(self, key: ast.AST) -> bool:
        """A key expression that can take unboundedly many per-request
        values: a shape attribute, a shape/length-named name, or a
        tuple/f-string containing one."""
        for sub in ast.walk(key):
            if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                return True
            if isinstance(sub, ast.Name) and self._shapeish(sub.id):
                return True
            if isinstance(sub, ast.Attribute) and self._shapeish(sub.attr):
                return True
            if isinstance(sub, ast.Call):
                fname = dotted_name(sub.func) or ""
                if fname == "len":
                    return True
        return False


# ----------------------------------------------------------------------
# GL010 — repeated host pull of the same device value in a loop
# ----------------------------------------------------------------------


class RepeatedHostPullRule(Rule):
    """``np.asarray(x)`` / ``jax.device_get(x)`` re-materializes the
    ENTIRE device array on the host every call. Doing it repeatedly for
    the same value inside one loop body — the typical shape is indexing
    one row per iteration, ``np.asarray(x_dev)[row]`` — pays the full
    device→host copy once per iteration for data that does not change
    across iterations. (The scheduler's prefill-emit loop did exactly
    this: every emitting row re-pulled the whole fetched block, per row,
    per window.) The fix is one hoisted host copy before (or memoized
    across) the loop, indexed per iteration.

    Conservative by design: only *literally identical* name/attribute
    arguments count, a rebind of the argument anywhere in the loop body
    disqualifies it (each iteration may legitimately pull a different
    array under the same name), and nested function bodies are skipped
    (a closure is not executed per iteration by the loop itself).
    ``jnp.asarray`` is the host→device direction and is GL008's
    business, not this rule's.
    """

    rule_id = "GL010"
    name = "repeated-host-pull"
    rationale = (
        "pulling the same device value to host more than once in a loop "
        "re-copies the full array per iteration; hoist one host copy "
        "before the loop and index it"
    )

    @staticmethod
    def _pull_arg(node: ast.AST) -> Optional[str]:
        """The pulled value's dotted name for ``np.asarray(x)`` /
        ``numpy.asarray(x)`` / ``jax.device_get(x)`` calls; None for
        anything else (including ``jnp.asarray`` — that is an upload)."""
        if not isinstance(node, ast.Call) or not node.args:
            return None
        fname = dotted_name(node.func) or ""
        short = fname.rsplit(".", 1)[-1]
        if short == "asarray":
            if fname.rsplit(".", 1)[0] not in ("np", "numpy"):
                return None
        elif short != "device_get":
            return None
        return dotted_name(node.args[0])

    @staticmethod
    def _loop_walk(loop: ast.AST) -> Iterator[ast.AST]:
        """Every node lexically inside the loop's body/orelse, skipping
        nested function/lambda bodies (not run per iteration by this
        loop) but descending into nested loops/ifs/withs."""
        stack = list(getattr(loop, "body", [])) + list(
            getattr(loop, "orelse", [])
        )
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _rebound_disqualifies(arg: str, rebound: set[str]) -> bool:
        """A pull of ``arg`` is disqualified when any rebound target is
        a dotted prefix of it (rebinding ``self``/``self.buf`` changes
        what ``self.buf.x`` resolves to) or vice versa (storing through
        ``self.buf.x`` may mutate the object ``self.buf`` holds)."""
        parts = arg.split(".")
        prefixes = {".".join(parts[: i + 1]) for i in range(len(parts))}
        if prefixes & rebound:
            return True
        return any(r.startswith(arg + ".") for r in rebound)

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        seen: set[tuple[int, str]] = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            rebound: set[str] = set()
            if isinstance(loop, (ast.For, ast.AsyncFor)):
                for t in ast.walk(loop.target):
                    if isinstance(t, ast.Name):
                        rebound.add(t.id)
            pulls: dict[str, list[ast.Call]] = {}
            for node in self._loop_walk(loop):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)
                ):
                    rebound.add(node.id)
                elif isinstance(
                    node, (ast.Attribute, ast.Subscript)
                ) and isinstance(node.ctx, (ast.Store, ast.Del)):
                    # self.buf = ... / self.buf[i] = ... inside the loop:
                    # pulls of self.buf (or anything reached through it)
                    # may see a different array each iteration, same as
                    # a bare-name rebind.
                    target = (
                        node if isinstance(node, ast.Attribute)
                        else node.value
                    )
                    dn = dotted_name(target)
                    if dn:
                        rebound.add(dn)
                arg = self._pull_arg(node)
                if arg is not None:
                    pulls.setdefault(arg, []).append(node)  # type: ignore[arg-type]
            for arg, calls in pulls.items():
                if len(calls) < 2:
                    continue
                if self._rebound_disqualifies(arg, rebound):
                    continue  # per-iteration value: each pull differs
                calls.sort(key=lambda c: (c.lineno, c.col_offset))
                anchor = calls[1]
                key = (anchor.lineno, arg)
                if key in seen:  # nested loops see the same pair twice
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, anchor,
                    f"`{arg}` is pulled to host {len(calls)} times in "
                    f"this loop — each call copies the full device "
                    f"array; hoist one host copy before the loop and "
                    f"index it per iteration",
                )


# ----------------------------------------------------------------------
# GL011 — per-row clock reads in scheduler emit/decode loops
# ----------------------------------------------------------------------


class PerRowClockRule(Rule):
    """``time.time()`` / ``time.monotonic()`` inside the per-row body of
    a scheduler emit/decode loop stamps per TOKEN: at window size k over
    S slots that is k×S clock syscalls per window of pure host overhead
    on the dispatch path, for timestamps whose consumers (ttft fields,
    phase timelines, histograms) cannot tell apart anyway — every row
    processed in one window/flush landed together. Timestamps belong at
    WINDOW granularity: read the clock once before the loop and share
    the value (exactly what ``_process_window``/``_flush_prefill_emits``
    do).

    Scope and conservatism: hot-path files only (the composed scheduler
    object), ``for`` loops only — ``while`` loops re-reading the clock
    are deadline/poll loops whose *condition* is the time, not per-row
    stamping — and nested function/lambda bodies are skipped (not run
    per iteration by this loop). ``while`` subtrees inside a flagged
    ``for`` are skipped for the same reason.
    """

    rule_id = "GL011"
    name = "per-row-clock"
    rationale = (
        "clock reads inside per-row emit/decode loop bodies are "
        "per-token host overhead; read the clock once per window/flush "
        "and share the timestamp"
    )

    _CLOCKS = frozenset((
        "time.time", "time.monotonic", "time.perf_counter",
        "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    ))

    def __init__(
        self,
        hot_path_files: Sequence[str] = (
            "serving/batcher.py",
            "serving/scheduler.py",
            "serving/engine.py",
        ),
    ) -> None:
        self._hot_files = tuple(hot_path_files)

    def applies_to(self, path: str) -> bool:
        return any(path.endswith(f) for f in self._hot_files)

    @staticmethod
    def _loop_walk(loop: ast.AST) -> Iterator[ast.AST]:
        """Nodes lexically inside the loop's body/orelse, skipping
        nested function/lambda bodies and ``while`` subtrees (poll
        loops legitimately re-read the clock per check)."""
        stack = list(getattr(loop, "body", [])) + list(
            getattr(loop, "orelse", [])
        )
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.While),
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        seen: set[tuple[int, int]] = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            for node in self._loop_walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if name not in self._CLOCKS:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:  # nested for-loops see the call twice
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, node,
                    f"`{name}()` inside a per-row loop body stamps per "
                    "token — host overhead on the dispatch path; read "
                    "the clock once per window/flush before the loop "
                    "and share the value",
                )


# ----------------------------------------------------------------------
# GL012 — blocking network I/O without an explicit timeout
# ----------------------------------------------------------------------


class BlockingIONoTimeoutRule(Rule):
    """A socket/HTTP-client call without an explicit timeout in the
    serving or service tier blocks a worker FOREVER when the peer
    blackholes (SYN dropped by a dead pod's floating IP, a remote that
    accepts and never answers). In the replica data plane that is not a
    hung request — it is a leaked thread per hang, an in-flight count
    that never drains, and a replica the pool cannot drain or retire.
    Every outbound call must state its budget: library defaults are
    either infinite (``socket``, ``urllib``) or owned by someone else's
    upgrade (``httpx``).

    Flagged (in ``serving/`` and ``service/`` only):

    * ``httpx.Client(...)`` / ``httpx.AsyncClient(...)`` constructed
      without a ``timeout=`` argument (per-request overrides exist, but
      the constructor default is the safety net every call inherits);
    * ``requests.get/post/…/request(...)`` without ``timeout=`` —
      requests' default is no timeout at all;
    * ``urllib.request.urlopen(...)`` without ``timeout`` (keyword or
      second positional);
    * ``socket.create_connection(addr)`` without a timeout (keyword or
      second positional) — inherits the global default, usually None.

    Conservative: only fully-dotted library entry points are matched
    (a method call on an already-configured client object carries its
    constructor's budget and is not re-flagged).
    """

    rule_id = "GL012"
    name = "blocking-io-no-timeout"
    rationale = (
        "outbound network calls in the serving/service tier must carry "
        "an explicit timeout; a blackholed peer otherwise parks the "
        "worker thread forever and the replica can never drain"
    )

    #: Constructors whose ``timeout=`` kwarg is the budget.
    _CLIENT_CTORS = frozenset(("httpx.Client", "httpx.AsyncClient"))
    #: requests' module-level verbs (timeout kwarg only).
    _REQUESTS_VERBS = frozenset(
        f"requests.{verb}" for verb in (
            "get", "post", "put", "patch", "delete", "head", "options",
            "request",
        )
    )
    #: Calls where the timeout may also be a positional argument:
    #: name → index of the timeout positional.
    _POSITIONAL_TIMEOUT = {
        "urllib.request.urlopen": 2,
        "socket.create_connection": 1,
    }

    def __init__(
        self, scoped_dirs: Sequence[str] = ("serving", "service")
    ) -> None:
        self._dirs = tuple(scoped_dirs)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(f"/{d}/" in norm or norm.startswith(f"{d}/")
                   for d in self._dirs)

    @staticmethod
    def _has_timeout_kwarg(call: ast.Call) -> bool:
        return any(
            kw.arg == "timeout" or kw.arg is None  # **kwargs may carry it
            for kw in call.keywords
        )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name in self._CLIENT_CTORS or name in self._REQUESTS_VERBS:
                if not self._has_timeout_kwarg(node):
                    yield self.finding(
                        ctx, node,
                        f"`{name}(...)` without an explicit `timeout=`: "
                        "a blackholed peer blocks this call forever; "
                        "state the budget at the call site",
                    )
            elif name in self._POSITIONAL_TIMEOUT:
                n_pos = self._POSITIONAL_TIMEOUT[name]
                if (
                    len(node.args) < n_pos + 1
                    and not self._has_timeout_kwarg(node)
                ):
                    yield self.finding(
                        ctx, node,
                        f"`{name}(...)` without a timeout (keyword or "
                        f"positional #{n_pos + 1}): inherits an infinite "
                        "default; state the budget at the call site",
                    )


# ----------------------------------------------------------------------
# GL013 — retry loops without backoff
# ----------------------------------------------------------------------


class RetryNoBackoffRule(Rule):
    """A retry loop that re-attempts I/O with NO delay between attempts
    is a thundering-herd amplifier: every client that failed at t₀
    retries at exactly t₀+ε, re-spiking the replica/service it just
    helped knock over — the failure mode the serving tier's own
    machinery (``RetryConfig``, the hedge budget, the tier-transfer
    backoff) exists to prevent. In ``serving/`` and ``service/`` every
    retry loop must back off (jittered, via ``RetryConfig.delay_s`` or
    an explicit sleep between attempts).

    Heuristics (deliberately conservative — plain iteration loops and
    adoption walks must not trip it):

    * a ``for`` loop counting attempts — target or ``range()`` argument
      names matching ``retry``/``retries``/``attempt`` — or a ``while``
      loop whose condition reads such a name;
    * whose body contains a ``try`` with at least one handler that
      swallows the failure (no ``raise`` anywhere in the handler — the
      retry-semantics marker: failures are absorbed so the next
      iteration re-attempts);
    * and whose body contains NO backoff: no call to anything named
      ``sleep``/``*.sleep``, no ``delay_s(...)``, and no ``RetryConfig``
      reference inside the loop.
    """

    rule_id = "GL013"
    name = "retry-no-backoff"
    rationale = (
        "retry loops in the serving/service tier must back off "
        "(jittered) between attempts; immediate re-attempts amplify "
        "the very overload they are retrying through"
    )

    _RETRYISH = ("retry", "retries", "attempt")

    def __init__(
        self, scoped_dirs: Sequence[str] = ("serving", "service")
    ) -> None:
        self._dirs = tuple(scoped_dirs)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(f"/{d}/" in norm or norm.startswith(f"{d}/")
                   for d in self._dirs)

    @classmethod
    def _retryish(cls, name: Optional[str]) -> bool:
        low = (name or "").lower()
        return any(marker in low for marker in cls._RETRYISH)

    @classmethod
    def _names_in(cls, node: ast.AST) -> Iterator[str]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                yield sub.id
            elif isinstance(sub, ast.Attribute):
                yield sub.attr

    @classmethod
    def _is_retry_loop(cls, loop: ast.AST) -> bool:
        if isinstance(loop, ast.For):
            if any(cls._retryish(n) for n in cls._names_in(loop.target)):
                return True
            it = loop.iter
            if (
                isinstance(it, ast.Call)
                and (dotted_name(it.func) or "") == "range"
            ):
                return any(
                    cls._retryish(n)
                    for arg in it.args for n in cls._names_in(arg)
                )
            return False
        if isinstance(loop, ast.While):
            return any(cls._retryish(n) for n in cls._names_in(loop.test))
        return False

    @staticmethod
    def _loop_body(loop: ast.AST) -> Iterator[ast.AST]:
        """Nodes lexically inside the loop body, skipping nested
        function/lambda bodies (not run per attempt by this loop)."""
        stack = list(getattr(loop, "body", [])) + list(
            getattr(loop, "orelse", [])
        )
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @classmethod
    def _swallows(cls, loop: ast.AST) -> bool:
        """True when some ``except`` handler in the loop body absorbs
        the failure (no raise in it) — the marker that the loop's next
        iteration is a RE-ATTEMPT, not plain iteration."""
        for node in cls._loop_body(loop):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not any(
                    isinstance(sub, ast.Raise)
                    for stmt in handler.body for sub in ast.walk(stmt)
                ):
                    return True
        return False

    @classmethod
    def _has_backoff(cls, loop: ast.AST) -> bool:
        for node in cls._loop_body(loop):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                short = name.rsplit(".", 1)[-1].lstrip("_")
                if short in ("sleep", "delay_s"):
                    return True
            if isinstance(node, ast.Name) and node.id == "RetryConfig":
                return True
            if isinstance(node, ast.Attribute) and node.attr == "RetryConfig":
                return True
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            if not self._is_retry_loop(loop):
                continue
            if not self._swallows(loop):
                continue
            if self._has_backoff(loop):
                continue
            yield self.finding(
                ctx, loop,
                "retry loop re-attempts with no backoff between "
                "attempts — failed peers get re-hit immediately and in "
                "lockstep; sleep a jittered delay (RetryConfig.delay_s) "
                "before each re-attempt",
            )


# ----------------------------------------------------------------------
# GL014 — cross-mesh host pulls / sharding-annotation drift
# ----------------------------------------------------------------------


class CrossMeshHostPullRule(Rule):
    """GSPMD-sharded serving (``TPU_TP``) puts the KV pool and params on
    a mesh; the serving hot path must stay device-count-agnostic. Two
    drift patterns break that silently:

    * **Cross-mesh host pull**: ``jax.device_get`` / ``np.asarray`` /
      ``np.array`` applied to the KV cache's planes (any expression
      mentioning ``cache``) gathers a SHARDED array to host — on a tp
      mesh that is an all-gather of pool HBM per call, and on a
      multi-host mesh it deadlocks outright. Block extraction must go
      through the export seam (``ops/kv_cache.export_blocks`` — one
      deliberate, documented bounce at prefill finalize), so host-pull
      calls inside ``export``-named functions are exempt.

    * **Sharding-annotation drift**: a bare one-argument
      ``jax.device_put(x)`` carries NO placement. In the mesh-aware hot
      modules every host→device upload must say where it lands (the
      engine's ``_up`` places replicated ``NamedSharding``s); an
      unannotated put commits to the default device and every sharded
      dispatch then drags the operand cross-mesh.

    Scope: the serving hot-path modules (scheduler/engine/programs/
    batcher) — boot/loader code may bounce deliberately.
    """

    rule_id = "GL014"
    name = "cross-mesh-host-pull"
    rationale = (
        "sharded serving must not host-pull cache planes outside the "
        "export seam, and hot-path uploads must carry an explicit "
        "sharding — unannotated transfers silently all-gather or "
        "replicate on a tp mesh"
    )

    #: numpy calls that materialize on host (module-qualified only —
    #: ``jnp.asarray`` stays on device, bare ``asarray`` is ambiguous).
    _PULLS = ("asarray", "array")
    _HOST_MODS = ("np", "numpy")

    def __init__(
        self,
        scoped_files: Sequence[str] = (
            "serving/scheduler.py",
            "serving/engine.py",
            "serving/programs.py",
            "serving/batcher.py",
        ),
    ) -> None:
        self._files = tuple(scoped_files)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(norm.endswith(f) for f in self._files)

    @staticmethod
    def _mentions_cache(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and "cache" in sub.attr.lower():
                return True
            if isinstance(sub, ast.Name) and "cache" in sub.id.lower():
                return True
        return False

    @classmethod
    def _is_host_pull(cls, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        parts = name.split(".")
        short = parts[-1]
        if short == "device_get":
            # jax.device_get / self._jax.device_get / bare device_get.
            return True
        if short in cls._PULLS and len(parts) >= 2:
            return parts[-2] in cls._HOST_MODS
        return False

    @staticmethod
    def _is_bare_device_put(call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        if name.rsplit(".", 1)[-1] != "device_put":
            return False
        operands = len(call.args) + len(call.keywords)
        return operands <= 1

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        # Function names walked INTO at each node, so seam functions
        # (export_*) exempt their whole lexical body.
        def visit(node: ast.AST, in_export: bool) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_export = in_export or "export" in node.name.lower()
            if isinstance(node, ast.Call):
                if (
                    not in_export
                    and self._is_host_pull(node)
                    and any(self._mentions_cache(a) for a in node.args)
                ):
                    yield self.finding(
                        ctx, node,
                        "host pull of KV-cache planes outside the export "
                        "seam — on a tp mesh this all-gathers sharded "
                        "pool HBM per call (and deadlocks multi-host); "
                        "ship blocks via ops/kv_cache.export_blocks",
                    )
                elif self._is_bare_device_put(node):
                    yield self.finding(
                        ctx, node,
                        "device_put without an explicit sharding/device "
                        "in a mesh-aware hot module — the operand "
                        "commits to the default device and sharded "
                        "dispatches drag it cross-mesh; place it with a "
                        "NamedSharding (the engine's _up helper)",
                    )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_export)

        yield from visit(tree, False)


# ----------------------------------------------------------------------
# GL015 — jax.jit created inside a per-request function body
# ----------------------------------------------------------------------


class JitInRequestPathRule(Rule):
    """``jax.jit``/``pjit`` CALLED inside a per-request function body
    builds a fresh jitted callable per call — its XLA cache is garbage-
    collected with it, so every request pays a full trace+compile (and
    the compile lock serializes the scheduler behind it). The serving
    discipline is: programs are built ONCE, at module scope or in a
    builder, and request paths only *call* them. This rule is the
    static twin of the runtime
    ``app_tpu_steady_state_recompiles_total`` counter
    (``serving/device_telemetry.py``): the counter catches shape drift
    through a correctly-built program, this catches the program being
    rebuilt at all.

    Exempt (not request paths):

    * module scope — the normal home of shared jits;
    * builder functions: ``_build_*`` / ``*_program`` (the
      ``serving/programs.py`` idiom), with exemption inherited by
      their nested defs (a decorator inside ``_build_llm_steps`` runs
      at build time);
    * constructors and boot/state rebuilds: ``__init__`` / ``_init*``
      / ``init*`` — they run per boot, not per request;
    * loader modules (``hf_loader.py`` / ``checkpoint.py`` /
      ``lora.py``): checkpoint ingestion jits leaf-transforms by
      design.

    Deliberate boot-path jits elsewhere carry an inline
    ``# graftlint: disable=GL015`` with their justification.
    """

    rule_id = "GL015"
    name = "jit-in-request-path"
    rationale = (
        "jax.jit created inside a per-request function recompiles on "
        "every call and serializes the scheduler behind the compile "
        "lock; build programs once (module scope or a _build_*/"
        "*_program builder) and only CALL them on request paths"
    )

    _EXEMPT_FILES = (
        "serving/hf_loader.py",
        "serving/checkpoint.py",
        "serving/lora.py",
    )

    def __init__(self, scoped_dirs: Sequence[str] = ("serving",)) -> None:
        self._dirs = tuple(scoped_dirs)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        if any(norm.endswith(f) for f in self._EXEMPT_FILES):
            return False
        return any(
            f"/{d}/" in norm or norm.startswith(f"{d}/")
            for d in self._dirs
        )

    @staticmethod
    def _exempt_name(name: str) -> bool:
        return (
            name.startswith("_build")
            or name.endswith("_program")
            or name == "__init__"
            or name.startswith("_init")
            or name.startswith("init")
        )

    @classmethod
    def _is_jit_maker(cls, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        short = name.rsplit(".", 1)[-1]
        if short in ("jit", "pjit"):
            return True
        if short == "partial":
            # partial(jax.jit, ...) — the decorator-factory idiom.
            return any(
                (dotted_name(a) or "").rsplit(".", 1)[-1]
                in ("jit", "pjit")
                for a in call.args
            )
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        # Exemption inherits downward (the GL014 in_export idiom): a
        # jit created anywhere inside a builder's lexical body runs at
        # build time, however deeply nested.
        def visit(
            node: ast.AST, in_function: bool, exempt: bool
        ) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                exempt = exempt or self._exempt_name(node.name)
                in_function = True
            if (
                in_function
                and not exempt
                and isinstance(node, ast.Call)
                and self._is_jit_maker(node)
            ):
                yield self.finding(
                    ctx, node,
                    "jax.jit created inside a per-request function — "
                    "each call rebuilds and recompiles the program; "
                    "build it once at module scope or in a _build_*/"
                    "*_program builder and call the built program here",
                )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_function, exempt)

        yield from visit(tree, False, False)


# ----------------------------------------------------------------------
# GL016 — request-controlled strings as metric label values
# ----------------------------------------------------------------------


class UnboundedMetricLabelRule(Rule):
    """A metric label whose value flows from a request-controlled
    string (tenant ids, header values) is an unbounded-cardinality
    time series: every distinct client-chosen value mints a new series,
    and an adversarial (or merely enthusiastic) client can blow up the
    exporter's memory and the scrape size. The serving discipline is
    the ``TPU_TENANT_LABEL_MAX`` clamp (``serving/tenant_ledger.py``):
    request-controlled values pass through a bounded label mapper
    (first-K distinct values, overflow folded into ``_other``) before
    they may reach a label. This rule is the static twin of that
    runtime clamp.

    Flagged (in ``serving/`` and ``service/`` only):

    * metrics-manager recording calls (``increment_counter`` /
      ``add_counter`` / ``record_histogram`` / ``set_gauge`` /
      ``delta_updown_counter``) whose *label value* positions (the odd
      elements of the trailing key/value pairs) contain a
      request-controlled expression — an identifier or attribute named
      ``tenant`` / ``tenant_id``, or a ``header``/``headers`` access;
    * prometheus-style ``.labels(...)`` calls with such a value.

    Clean: the value is wrapped in a clamp/allowlist helper — a call to
    a function whose name is ``label_for`` / ``clamp_label`` or ends
    with ``_label`` (the bounded-mapper naming convention).

    Conservative: only the marker names above taint; a label value
    computed from engine-owned state (model names, reason literals,
    outcome vocabularies) never matches.
    """

    rule_id = "GL016"
    name = "unbounded-metric-label"
    rationale = (
        "request-controlled strings (tenant ids, headers) as metric "
        "label values are unbounded cardinality; route them through a "
        "bounded clamp/allowlist helper (TPU_TENANT_LABEL_MAX idiom) "
        "before they reach a label"
    )

    #: Recorder method → index of the first label element in args
    #: (after name [+ value]); the trailing args alternate key, value.
    _RECORDERS = {
        "increment_counter": 1,
        "add_counter": 2,
        "record_histogram": 2,
        "set_gauge": 2,
        "delta_updown_counter": 2,
    }
    _TAINT = frozenset(("tenant", "tenant_id", "header", "headers"))
    _CLAMPS = frozenset(("label_for", "clamp_label"))

    def __init__(
        self, scoped_dirs: Sequence[str] = ("serving", "service")
    ) -> None:
        self._dirs = tuple(scoped_dirs)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(
            f"/{d}/" in norm or norm.startswith(f"{d}/")
            for d in self._dirs
        )

    @classmethod
    def _is_clamped(cls, node: ast.AST) -> bool:
        """The value is a clamp-helper call — bounded by construction."""
        if not isinstance(node, ast.Call):
            return False
        name = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
        return name in cls._CLAMPS or name.endswith("_label")

    @classmethod
    def _tainted(cls, node: ast.AST) -> bool:
        if cls._is_clamped(node):
            return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in cls._TAINT:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in cls._TAINT:
                return True
        return False

    def _check_value(
        self, ctx: FileContext, call: ast.Call, value: ast.AST
    ) -> Iterator[Finding]:
        if self._tainted(value):
            yield self.finding(
                ctx, call,
                "request-controlled string as a metric label value — "
                "unbounded series cardinality; clamp it through a "
                "bounded label mapper (label_for/*_label; "
                "TPU_TENANT_LABEL_MAX idiom) first",
            )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            attr = node.func.attr
            if attr in self._RECORDERS:
                first = self._RECORDERS[attr]
                labels = node.args[first:]
                # Values sit at the odd offsets of the key/value tail.
                for i in range(1, len(labels), 2):
                    for f in self._check_value(ctx, node, labels[i]):
                        yield f
                        break
            elif attr == "labels":
                # prometheus_client idiom: .labels(v1, k2=v2).
                for value in (*node.args, *(
                    kw.value for kw in node.keywords
                )):
                    found = False
                    for f in self._check_value(ctx, node, value):
                        yield f
                        found = True
                        break
                    if found:
                        break


# ----------------------------------------------------------------------
# GL017 — control-loop threshold comparisons without hysteresis
# ----------------------------------------------------------------------


class ThresholdNoHysteresisRule(Rule):
    """A control loop that flips state the first time a noisy load
    signal crosses a threshold oscillates: one bad tick trips the
    actuator, the next good tick untrips it, and the system flaps at
    the noise frequency instead of responding to sustained pressure.
    Every controller in this repo that earned its keep — the watchdog,
    the pool scaler's sustain windows, the brownout ladder
    (``serving/brownout.py``), the hedge budget — pairs its thresholds
    with a sustain window, an enter/exit hysteresis band, or a budget
    guard. This rule is the static twin of that discipline.

    Flagged (in ``serving/`` and ``service/`` only): an ``if`` whose
    test compares a *signal* expression (a name mentioning ``burn``,
    ``headroom``, ``load_per_replica``, ``occupancy``, or
    ``saturation``) against a *threshold* expression (a name mentioning
    ``threshold``, ``floor``, ``enter``, ``exit``, ``watermark``, or
    ``limit`` — the env-derived-knob naming convention), where the
    branch body **assigns instance state** (``self.x = ...`` — a level,
    a mode, an open/tripped flag), and the enclosing function shows no
    guard evidence: no name mentioning ``since`` / ``sustain`` /
    ``streak`` / ``consecutive`` / ``hysteresis`` / ``budget`` /
    ``window``.

    Clean: shedding or raising inside the branch (a per-request
    decision, not controller state), sustain-anchor idioms
    (``self._pressure_since``), ``Sustain``/``HedgeBudget``-style
    guards, and comparisons whose sides don't carry both marker
    families. Conservative by construction — it looks for the *shape*
    of a flapping controller, not for every threshold.
    """

    rule_id = "GL017"
    name = "threshold-no-hysteresis"
    rationale = (
        "state flipped on a raw signal-vs-threshold comparison flaps "
        "at the noise frequency; pair the threshold with a sustain "
        "window or an enter/exit hysteresis band (the "
        "serving/brownout.py ladder idiom)"
    )

    _SIGNALS = ("burn", "headroom", "load_per_replica", "occupancy",
                "saturation")
    _THRESHOLDS = ("threshold", "floor", "enter", "exit", "watermark",
                   "limit")
    _GUARDS = ("since", "sustain", "streak", "consecutive",
               "hysteresis", "budget", "window")

    def __init__(
        self, scoped_dirs: Sequence[str] = ("serving", "service")
    ) -> None:
        self._dirs = tuple(scoped_dirs)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(
            f"/{d}/" in norm or norm.startswith(f"{d}/")
            for d in self._dirs
        )

    @staticmethod
    def _idents(node: ast.AST) -> list[str]:
        """Every identifier string mentioned in the expression."""
        out: list[str] = []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                out.append(sub.id.lower())
            elif isinstance(sub, ast.Attribute):
                out.append(sub.attr.lower())
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(sub.name.lower())
        return out

    @classmethod
    def _mentions(cls, node: ast.AST, markers: Sequence[str]) -> bool:
        return any(
            m in ident for ident in cls._idents(node) for m in markers
        )

    @classmethod
    def _threshold_compare(cls, test: ast.AST) -> bool:
        """One side mentions a signal, the other a threshold knob."""
        for node in ast.walk(test):
            if not isinstance(node, ast.Compare) or len(node.comparators) != 1:
                continue
            left, right = node.left, node.comparators[0]
            if (
                cls._mentions(left, cls._SIGNALS)
                and cls._mentions(right, cls._THRESHOLDS)
            ) or (
                cls._mentions(right, cls._SIGNALS)
                and cls._mentions(left, cls._THRESHOLDS)
            ):
                return True
        return False

    @staticmethod
    def _flips_self_state(body: Sequence[ast.stmt]) -> bool:
        """The branch assigns an attribute on ``self`` — controller
        state, as opposed to shedding/raising a request decision."""
        for stmt in body:
            for node in ast.walk(stmt):
                targets: list[ast.expr] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for target in targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        return True
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # Guard evidence anywhere in the function exempts every
            # comparison in it: sustain anchors and hysteresis pairs
            # live next to the thresholds they guard.
            if self._mentions(fn, self._GUARDS):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.If):
                    continue
                if not self._threshold_compare(node.test):
                    continue
                if not self._flips_self_state(node.body):
                    continue
                yield self.finding(
                    ctx, node,
                    "state flipped on a raw threshold comparison of a "
                    "load signal — one noisy tick trips it and the "
                    "next untrips it; add a sustain window or an "
                    "enter/exit hysteresis pair (the brownout-ladder "
                    "idiom)",
                )


# ----------------------------------------------------------------------
# registry
# ----------------------------------------------------------------------

ALL_RULES: "tuple[type[Rule], ...]" = (
    HostDeviceSyncRule,
    TracerBranchRule,
    RecompilationHazardRule,
    BlockingCallRule,
    LockDisciplineRule,
    ExceptionSwallowRule,
    DonatedBufferReuseRule,
    ScanBodyAsarrayRule,
    JitCacheGrowthRule,
    RepeatedHostPullRule,
    PerRowClockRule,
    BlockingIONoTimeoutRule,
    RetryNoBackoffRule,
    CrossMeshHostPullRule,
    JitInRequestPathRule,
    UnboundedMetricLabelRule,
    ThresholdNoHysteresisRule,
)
# (GL018/GL019 are appended to ALL_RULES below their definitions — the
# tuple predates them and later rules are defined after the registry.)


# ----------------------------------------------------------------------
# GL018 — host pull inside the device transfer leg
# ----------------------------------------------------------------------


class HostPullInDeviceLegRule(Rule):
    """The disaggregated-tier DEVICE leg exists to ship KV blocks
    pool→pool without touching host memory: per-block jitted extraction
    on the exporter, an explicit sharding-aware ``device_put``, and a
    donated jitted write on the importer. Its whole value evaporates —
    silently — if any step materializes a cache plane on host:
    ``jax.device_get`` or ``np.asarray``/``np.array`` of a cache/plane
    expression inside device-leg code re-introduces the PCIe round trip
    the leg was built to remove, and on a GSPMD-sharded pool it
    all-gathers shard HBM per call. The naming convention IS the
    contract: functions named ``*_device_leg`` or ``paged_move*`` are
    the device leg, and a host pull of plane data inside one is always
    a bug (the deliberate host bounce lives in ``export*`` functions,
    GL014's documented seam).
    """

    rule_id = "GL018"
    name = "host-pull-in-device-leg"
    rationale = (
        "the device transfer leg must never bounce cache planes "
        "through host memory — a device_get/np.asarray inside "
        "*_device_leg/paged_move* code silently re-adds the PCIe "
        "round trip (and all-gathers sharded pool HBM) the leg "
        "exists to remove"
    )

    _PULLS = ("asarray", "array")
    _HOST_MODS = ("np", "numpy")
    #: expression names that identify KV-plane data in transfer code.
    _PLANE_HINTS = ("cache", "plane", "blk", "block", "payload", "k_s",
                    "v_s")

    @staticmethod
    def _is_device_leg_name(name: str) -> bool:
        low = name.lower()
        return low.endswith("_device_leg") or low.startswith("paged_move")

    @classmethod
    def _mentions_plane(cls, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            label = None
            if isinstance(sub, ast.Attribute):
                label = sub.attr.lower()
            elif isinstance(sub, ast.Name):
                label = sub.id.lower()
            if label and any(h in label for h in cls._PLANE_HINTS):
                return True
        return False

    @classmethod
    def _is_host_pull(cls, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        parts = name.split(".")
        short = parts[-1]
        if short == "device_get":
            return True
        if short in cls._PULLS and len(parts) >= 2:
            return parts[-2] in cls._HOST_MODS
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        # The device-leg property inherits into nested defs (a helper
        # closure inside a device-leg function is still the device leg).
        def visit(node: ast.AST, in_leg: bool) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_leg = in_leg or self._is_device_leg_name(node.name)
            if (
                in_leg
                and isinstance(node, ast.Call)
                and self._is_host_pull(node)
                and any(self._mentions_plane(a) for a in node.args)
            ):
                yield self.finding(
                    ctx, node,
                    "host pull of cache-plane data inside the device "
                    "transfer leg — this re-adds the host bounce the "
                    "leg exists to remove; keep planes on device "
                    "(jitted extract/move + explicit device_put) or "
                    "route through the export* host-bounce seam",
                )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_leg)

        yield from visit(tree, False)


ALL_RULES = ALL_RULES + (HostPullInDeviceLegRule,)


# ----------------------------------------------------------------------
# GL019 — device sync outside the designated device-window seam
# ----------------------------------------------------------------------


class SyncOutsideDeviceWaitRule(Rule):
    """The scheduler loop's phase attribution (``serving/
    loop_profiler.py``) rests on one structural contract: the loop
    blocks on the device ONLY inside the designated device-window seam
    (``_process_window``'s fetch, ``_dispatch_window``'s lockstep
    barrier). A ``block_until_ready`` / ``.item()`` / ``float()``-on-a-
    device-value call inside any *other* scheduler-loop-phase function
    silently converts a host phase into a hidden device wait: the
    ``host_overhead_ratio`` signal then blames Python for time the
    device actually took (or vice versa), and the sync serializes the
    pipelined windows exactly like a GL001 hot-path sync — except
    invisibly, because the phase gauges say "prefill" or "reap".

    Scope: scheduler files only (``serving/scheduler.py`` — every
    function there IS loop-phase code). The seam functions are exempt
    by name; device values are recognized by the codebase's ``*_dev``
    naming convention, with call results excluded (``float(pull(x_dev)
    [row])`` is a host read of an already-pulled array, not a sync).
    Deliberate waits elsewhere (the multi-process lockstep barriers)
    carry an inline disable — the justification doubles as
    documentation.
    """

    rule_id = "GL019"
    name = "sync-outside-device-wait"
    rationale = (
        "a device sync inside a host loop phase hides a device wait "
        "from the per-phase attribution and serializes the pipelined "
        "windows; block on the device only inside the designated "
        "device-window seam (_process_window/_dispatch_window) or "
        "justify the barrier with an inline disable"
    )

    #: The designated device-wait seam: the only scheduler functions
    #: that may legitimately block on the device.
    _SEAM = frozenset(("_process_window", "_dispatch_window"))

    def __init__(
        self, scheduler_files: Sequence[str] = ("serving/scheduler.py",)
    ) -> None:
        self._files = tuple(scheduler_files)

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(norm.endswith(f) for f in self._files)

    @staticmethod
    def _dev_root(node: ast.AST) -> bool:
        """True when the expression is a Name/Attribute/Subscript chain
        whose ROOT identifier follows the ``*_dev`` device-plane naming
        convention. Call results are excluded: a pulled host copy of a
        device array is not a sync."""
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if (
                isinstance(node, ast.Attribute)
                and node.attr.endswith("_dev")
            ):
                return True
            node = node.value
        return isinstance(node, ast.Name) and node.id.endswith("_dev")

    @classmethod
    def _is_sync(cls, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        short = name.rsplit(".", 1)[-1]
        if short == "block_until_ready":
            return True
        if (
            short == "item"
            and isinstance(call.func, ast.Attribute)
            and cls._dev_root(call.func.value)
        ):
            return True
        if (
            isinstance(call.func, ast.Name)
            and call.func.id == "float"
            and len(call.args) == 1
            and cls._dev_root(call.args[0])
        ):
            return True
        return False

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        # Seam-ness inherits into nested defs (a helper closure inside
        # _process_window is still the seam); everything else in a
        # scheduler file is loop-phase code.
        def visit(node: ast.AST, in_seam: bool) -> Iterator[Finding]:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_seam = in_seam or node.name in self._SEAM
            if (
                not in_seam
                and isinstance(node, ast.Call)
                and self._is_sync(node)
            ):
                yield self.finding(
                    ctx, node,
                    "device sync inside a scheduler-loop phase function "
                    "outside the device-window seam — this hides a "
                    "device wait from the per-phase attribution "
                    "(host_overhead_ratio lies) and serializes the "
                    "pipelined windows; move the wait into "
                    "_process_window or justify the barrier inline",
                )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_seam)

        yield from visit(tree, False)


ALL_RULES = ALL_RULES + (SyncOutsideDeviceWaitRule,)


class AckBeforeResultRule(Rule):
    """At-least-once delivery dies at exactly one line: the consumer
    that acks a message *before* its result is safely out. An ack is
    the broker's permission to forget — if the handler then crashes
    between the ack and the reply publish (or the terminal future
    resolution), the message is gone and the reply never happens: the
    silent-loss bug class the async serving plane (ISSUE 18) exists to
    prevent. The correct order is always publish-then-ack; a replayed
    duplicate is the dedup ledger's problem, a lost message is nobody's.

    Heuristic: inside one function body in ``pubsub/``/``serving/``
    scope, flag a ``.ack(`` call that lexically precedes a result seam
    — a ``publish``-named call (``publish``/``_publish_reply``/...), a
    dead-letter handoff, or a terminal ``set_result``/``set_exception``
    — later in the same body. A function that only acks (the dedup
    replay path, where the reply already went out) has no seam after
    the ack and does not fire; nested defs are separate bodies.
    Deliberate ack-first consumers (at-MOST-once by design) carry an
    inline disable — the justification doubles as documentation.
    """

    rule_id = "GL023"
    name = "ack-before-result"
    rationale = (
        "acking a message before its result publish / terminal seam "
        "converts at-least-once into at-most-once: a crash between the "
        "ack and the publish loses the message with no redelivery; "
        "publish the result first and let the dedup ledger absorb "
        "replayed duplicates, or justify at-most-once inline"
    )

    #: Call names that terminate a handler's result: the reply/DLQ
    #: publish and the future's terminal transitions.
    _SEAMS = ("publish", "dead_letter", "set_result", "set_exception")

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(
            f"/{d}/" in norm or norm.startswith(f"{d}/")
            for d in ("pubsub", "serving")
        )

    @classmethod
    def _is_seam(cls, call: ast.Call) -> bool:
        name = dotted_name(call.func) or ""
        short = name.rsplit(".", 1)[-1].lstrip("_")
        return any(s in short for s in cls._SEAMS)

    @staticmethod
    def _is_ack(call: ast.Call) -> bool:
        return (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "ack"
        )

    @staticmethod
    def _body_calls(fn: ast.AST) -> "list[ast.Call]":
        """Every Call in ``fn``'s own body, nested defs excluded (a
        nested handler is its own consumer body)."""
        calls: list[ast.Call] = []
        stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return calls

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls = self._body_calls(fn)
            seam_lines = [c.lineno for c in calls if self._is_seam(c)]
            if not seam_lines:
                continue
            last_seam = max(seam_lines)
            for call in calls:
                if self._is_ack(call) and call.lineno < last_seam:
                    yield self.finding(
                        ctx, call,
                        f"`{fn.name}` acks before its result publish / "
                        "terminal seam in the same body — a crash "
                        "between this ack and the publish loses the "
                        "message with no redelivery (at-least-once "
                        "becomes at-most-once); publish first, ack "
                        "last, and let the dedup ledger absorb "
                        "replays",
                    )


ALL_RULES = ALL_RULES + (AckBeforeResultRule,)


# ----------------------------------------------------------------------
# GL024 — transfer-handle acquisition without a budget
# ----------------------------------------------------------------------


class HandleNoDeadlineRule(Rule):
    """The multi-host disaggregation plane (ISSUE 19) moves KV blocks
    through *acquisition* calls — redeeming a dma claim ticket
    (``dma_fetch``), asking a remote prefill source for blocks
    (``fetch_prefilled``), waiting on the exporting scheduler
    (``export_cached``) — and every one of them blocks on another
    PROCESS. A stalled exporter, a partitioned source, or a
    half-killed pod parks the caller forever unless the call carries
    its budget; unlike an in-proc lock there is no supervisor on the
    other side to break the wait. The failure matrix's slow-loris and
    partition rows only degrade one rung because every acquisition
    site states a ``deadline=``/``timeout_s=`` bound.

    Heuristic: in ``serving/``/``service/`` scope, flag a call whose
    name ends in one of the acquisition verbs unless it carries a
    budget keyword (``deadline`` / ``timeout`` / ``timeout_s`` /
    ``wait_s`` / ``read_timeout_s`` / ``connect_timeout_s``) or a
    ``**kwargs`` splat that may. Raw socket/HTTP calls stay GL012's
    business — this rule is about the transfer-handle layer above
    them, where the budget is a ``Deadline`` threaded from the
    request.
    """

    rule_id = "GL024"
    name = "handle-no-deadline"
    rationale = (
        "cross-process transfer-handle acquisitions (dma_fetch / "
        "fetch_prefilled / export_cached) block on another process; "
        "without a deadline= / timeout_s= budget a stalled or "
        "partitioned peer parks the caller forever and the failure "
        "matrix's one-rung degradation contract breaks"
    )

    #: Call-name suffixes that acquire a cross-process transfer
    #: handle or wait on one being produced.
    _ACQUIRERS = frozenset(
        ("dma_fetch", "fetch_prefilled", "export_cached")
    )
    #: Keywords that state the budget.
    _BUDGET_KWARGS = frozenset((
        "deadline", "timeout", "timeout_s", "wait_s",
        "read_timeout_s", "connect_timeout_s",
    ))

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return any(
            f"/{d}/" in norm or norm.startswith(f"{d}/")
            for d in ("serving", "service")
        )

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            short = name.rsplit(".", 1)[-1]
            if short not in self._ACQUIRERS:
                continue
            if any(
                kw.arg is None or kw.arg in self._BUDGET_KWARGS
                for kw in node.keywords
            ):
                continue
            yield self.finding(
                ctx, node,
                f"`{name}(...)` acquires a cross-process transfer "
                "handle without a budget — thread the request's "
                "`deadline=` (or a `timeout_s=` bound) into the call "
                "so a stalled/partitioned peer degrades one rung "
                "instead of parking this thread forever",
            )


ALL_RULES = ALL_RULES + (HandleNoDeadlineRule,)


class DuplicatedLogitsPathRule(Rule):
    """The speculative-decoding divergence bug (ROADMAP direction 1,
    fixed in ISSUE 20) was exactly this: ``serving/programs.py`` called
    a *second* transformer forward (``transformer_verify_step``) that
    recomputed decode-position logits with a batched ``[S, G+1]``
    contraction shape. bf16 reductions are order-sensitive, so the
    batched contraction's different accumulation order flipped near-tie
    argmaxes relative to the one-position decode step — 4/8 bench
    prompts diverged token-for-token. The fix reuses the decode-step
    program per candidate position, making the verify logits identical
    by construction; any device program in serving/ that emits tokens
    must derive its logits from that one shared builder.

    Heuristic: in ``serving/`` scope, flag a call whose terminal name
    ends in ``verify_step`` — the models-layer batched-verify builders
    keep that suffix, and calling one from the serving plane
    reintroduces a logits path with its own contraction shape. A
    deliberate tolerance-checked use (e.g. a models-layer parity test
    helper) carries an inline disable.
    """

    rule_id = "GL025"
    name = "duplicated-logits-path"
    rationale = (
        "a second transformer forward in the serving plane recomputes "
        "decode logits with a different contraction shape; bf16 "
        "reduction order differs between shapes, so near-tie argmaxes "
        "flip and token streams diverge from the decode window — "
        "derive serving logits from the shared decode-step builder"
    )

    def applies_to(self, path: str) -> bool:
        norm = path.replace("\\", "/")
        return "/serving/" in norm or norm.startswith("serving/")

    def check(self, tree: ast.Module, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            short = name.rsplit(".", 1)[-1]
            if not short.endswith("verify_step"):
                continue
            yield self.finding(
                ctx, node,
                f"`{name}(...)` is a second decode-logits path: its "
                "batched contraction shape accumulates bf16 in a "
                "different order than the decode step, flipping "
                "near-tie argmaxes — run the shared decode-step "
                "builder (`transformer_decode_step`) over the "
                "candidate window instead so verify logits are "
                "bit-identical by construction",
            )


ALL_RULES = ALL_RULES + (DuplicatedLogitsPathRule,)


# ----------------------------------------------------------------------
# GL020–GL022 — project-wide concurrency rules (two-phase engine)
# ----------------------------------------------------------------------


class _ConcurrencyRule(ProjectRule):
    """Shared scoping for the project-wide concurrency rules: findings
    are only *reported* under the concurrency dirs (serving/, service/
    by default) — the index itself still spans every scanned file so
    cross-package call edges resolve."""

    def __init__(
        self, concurrency_dirs: Sequence[str] = ("serving", "service")
    ) -> None:
        self._dirs = tuple(concurrency_dirs)

    def applies_to(self, path: str) -> bool:
        parts = path.replace("\\", "/").split("/")
        return any(d in parts[:-1] for d in self._dirs)


class UnguardedSharedStateRule(_ConcurrencyRule):
    """An attribute consistently written under a lock in one method but
    accessed lock-free in another method reachable from a *different*
    thread root has no happens-before edge: the scheduler loop, prober,
    scaler, watchdog, and request threads all run concurrently, and a
    torn read across that mesh is exactly the bug class PR 14's
    lazy-init race belonged to.

    Two binding modes, strongest first:

    * **declared** — ``self._epoch = 0  # graftlint: guarded-by=_lock``
      binds the attribute to the named lock; every lock-free read *or*
      write outside ``__init__`` is flagged.
    * **inferred** — majority-access fallback: if a lock is held for
      at least two accesses (one of them a write) and for strictly more
      accesses than run lock-free, the attribute is treated as guarded
      by it and lock-free *writes* are flagged (reads are too noisy to
      infer without a declaration).

    Either way a finding additionally requires the attribute to be
    reachable from at least two distinct thread roots — single-thread
    state cannot race, however inconsistent its locking looks.
    """

    rule_id = "GL020"
    name = "unguarded-shared-state"
    rationale = (
        "an attribute written under a lock in one thread but accessed "
        "lock-free from another has no happens-before edge; hold the "
        "lock everywhere or declare the actual discipline with "
        "# graftlint: guarded-by=<lock>"
    )

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        by_attr: dict[tuple[str, str], list[AttrAccess]] = {}
        for fn in index.functions.values():
            # Locks guaranteed held on entry count as held at every
            # access: `# Callers hold self._lock` helpers are guarded
            # by their call sites, not their own body.
            entry = index.entry_locks(fn.key)
            for acc in fn.accesses:
                if acc.in_init:
                    continue
                if entry:
                    acc = replace(acc, locks_held=acc.locks_held | entry)
                by_attr.setdefault((acc.group, acc.attr), []).append(acc)
        for (group, attr), accesses in sorted(by_attr.items()):
            declared = index.guarded_by.get((group, attr))
            lock = declared or self._infer_lock(accesses)
            if lock is None:
                continue
            roots: set[str] = set()
            for acc in accesses:
                roots |= index.roots_of(acc.func)
            if len(roots) < 2:
                continue
            lock_name = lock.rsplit(".", 1)[-1]
            how = "declared" if declared else "inferred"
            for acc in sorted(accesses, key=lambda a: (a.path, a.line)):
                if lock in acc.locks_held:
                    continue
                if not declared and not acc.write:
                    continue
                if not index.roots_of(acc.func):
                    continue
                kind = "write to" if acc.write else "read of"
                yield self.project_finding(
                    index, acc.path, acc.line,
                    f"lock-free {kind} `self.{attr}` ({how} guarded by "
                    f"`{lock_name}`, and reachable from threads: "
                    f"{', '.join(sorted(roots))}); hold `{lock_name}` "
                    "here or justify the lock-free access inline",
                    col=acc.col,
                )

    @staticmethod
    def _infer_lock(accesses: list[AttrAccess]) -> Optional[str]:
        locked: dict[str, int] = {}
        locked_writes: dict[str, int] = {}
        unlocked = 0
        for acc in accesses:
            if not acc.locks_held:
                unlocked += 1
            for lock in acc.locks_held:
                locked[lock] = locked.get(lock, 0) + 1
                if acc.write:
                    locked_writes[lock] = locked_writes.get(lock, 0) + 1
        best: Optional[str] = None
        for lock, n in sorted(locked.items()):
            if locked_writes.get(lock, 0) < 1:
                continue
            if n < 2 or n <= unlocked:
                continue
            if best is None or n > locked[best]:
                best = lock
        return best


def may_acquire_while_holding(
    index: ProjectIndex,
) -> dict[tuple[str, str], tuple[str, int, tuple[str, ...]]]:
    """The static may-acquire-while-holding edge set GL021 runs cycle
    detection over: ``(held, acquired) -> (path, line, chain)`` — one
    example site per ordered pair where ``acquired`` is taken (directly
    or transitively through the call graph) inside a ``with held:``
    region. Shared with ``/debug/lockgraph``, which diffs this model
    against the runtime graph ``lockcheck.order_graph()`` learned."""
    witness: dict[tuple[str, str], tuple[str, int, tuple[str, ...]]] = {}
    for fn in index.functions.values():
        for held_key, region in fn.regions:
            if held_key.startswith("?."):
                continue
            # nested acquisitions in the same function body
            for acq in fn.acquisitions:
                if acq.lock == held_key or acq.lock.startswith("?."):
                    continue
                if region.holds_at(acq.line) or (
                    region.lineno < acq.line <= region.end_lineno
                ):
                    witness.setdefault(
                        (held_key, acq.lock),
                        (acq.path, acq.line, (fn.name,)),
                    )
            # transitive acquisitions through calls made under the
            # *lexical* region — deliberately ignoring manual
            # release windows: a release-around seam still relies
            # on timing, and the finding's inline disable is where
            # that reliance gets documented.
            for call in fn.calls:
                if call.callee is None:
                    continue
                if not (
                    region.lineno < call.line <= region.end_lineno
                ):
                    continue
                for lock, chain in index.may_acquire(
                    call.callee
                ).items():
                    # lock == held_key stays IN: re-acquiring a
                    # plain Lock through a call chain is a self-
                    # deadlock (_cycle_findings exempts RLocks).
                    if lock.startswith("?."):
                        continue
                    witness.setdefault(
                        (held_key, lock),
                        (call.path, call.line, (fn.name,) + chain),
                    )
    return witness


class LockOrderInversionRule(_ConcurrencyRule):
    """Two locks acquired in opposite orders on two code paths deadlock
    the moment both paths run concurrently — the exact hazard PR 4
    dodged *manually* by releasing the engine submit lock around pool
    adoption. This rule builds the may-acquire-while-holding graph
    (nested ``with`` blocks plus transitive acquisitions through the
    call graph) and flags every edge that participates in a cycle,
    including a plain-Lock self-cycle (re-acquiring a non-reentrant
    lock through a call chain is a self-deadlock, not an inversion,
    but the fix is the same).

    Only locks the index resolved to a concrete owner participate —
    an unresolved ``obj._lock`` would conflate every class's ``_lock``
    into one node and invent cycles that cannot happen.
    """

    rule_id = "GL021"
    name = "lock-order-inversion"
    rationale = (
        "two locks taken in opposite orders on concurrent paths "
        "deadlock under the wrong interleaving; pick one global order "
        "or release the outer lock around the foreign acquisition"
    )

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        yield from self._cycle_findings(
            index, may_acquire_while_holding(index)
        )

    def _cycle_findings(
        self,
        index: ProjectIndex,
        witness: dict[tuple[str, str], tuple[str, int, tuple[str, ...]]],
    ) -> Iterator[Finding]:
        adj: dict[str, set[str]] = {}
        for left, right in witness:
            adj.setdefault(left, set()).add(right)
            adj.setdefault(right, set())
        sccs = _tarjan(adj)
        in_cycle: dict[str, int] = {}
        for i, scc in enumerate(sccs):
            if len(scc) > 1:
                for node in scc:
                    in_cycle[node] = i
        findings: list[Finding] = []
        for (left, right), (path, line, chain) in sorted(witness.items()):
            self_cycle = left == right or (
                (right, left) in witness and left != right
            )
            same_scc = (
                in_cycle.get(left) is not None
                and in_cycle.get(left) == in_cycle.get(right)
            )
            if not (same_scc or self_cycle):
                continue
            if left == right:
                kind = index.locks.get(left)
                if kind is not None and kind.kind == "RLock":
                    continue  # re-entrant by design
                msg = (
                    f"`{_lock_label(left)}` may be re-acquired while "
                    f"already held (via {' -> '.join(chain)}); a plain "
                    "Lock self-deadlocks here"
                )
            else:
                back = witness.get((right, left))
                where = (
                    f"; the reverse order is taken at {back[0]}:{back[1]}"
                    if back is not None else
                    " (reverse path closes the cycle elsewhere)"
                )
                msg = (
                    "lock-order inversion: acquires "
                    f"`{_lock_label(right)}` while holding "
                    f"`{_lock_label(left)}` (via {' -> '.join(chain)})"
                    f"{where}; pick one global order or release "
                    f"`{_lock_label(left)}` around the acquisition"
                )
            findings.append(
                self.project_finding(index, path, line, msg)
            )
        yield from findings


class BlockingUnderLockRule(_ConcurrencyRule):
    """A ``with <lock>:`` region that reaches a blocking primitive —
    ``block_until_ready``/``device_get`` (device sync), HTTP, ``sleep``,
    a blocking ``queue.get`` — stalls every thread contending for that
    lock for the primitive's full duration: a device sync under the
    submit lock turns one slow window into a serving-wide convoy. The
    per-file GL004-era checks only see the direct body; this rule
    follows the call graph, so a helper three frames down still trips
    it.

    Condition regions are exempt (waiting is their job), as is code
    inside a manual release window (the lock is not actually held
    there).
    """

    rule_id = "GL022"
    name = "blocking-under-lock"
    rationale = (
        "a blocking call while holding a lock convoys every thread "
        "contending for it; move the wait outside the critical section "
        "or justify the hold inline"
    )

    def check_project(self, index: ProjectIndex) -> Iterator[Finding]:
        for key in sorted(index.functions):
            fn = index.functions[key]
            for held_key, region in fn.regions:
                lock_def = index.locks.get(held_key)
                if lock_def is not None and lock_def.kind == "Condition":
                    continue
                label = _lock_label(held_key)
                for name, line, col in fn.blocking:
                    if region.holds_at(line):
                        yield self.project_finding(
                            index, fn.path, line,
                            f"blocking call `{name}` while holding "
                            f"`{label}`; every thread contending for "
                            "the lock stalls behind it",
                            col=col,
                        )
                for call in fn.calls:
                    if call.callee is None:
                        continue
                    if not region.holds_at(call.line):
                        continue
                    for name, chain in sorted(
                        index.may_block(call.callee).items()
                    ):
                        yield self.project_finding(
                            index, fn.path, call.line,
                            f"call chain {' -> '.join((fn.name,) + chain)} "
                            f"reaches blocking `{name}` while holding "
                            f"`{label}`; move the wait outside the "
                            "critical section",
                            col=call.col,
                        )


def _lock_label(key: str) -> str:
    """Human name for a lock key: ``Engine._submit_lock`` for instance
    locks, the bare name for module-level ones."""
    if ":" in key:
        return key.rsplit(":", 1)[-1]
    return key


def _tarjan(adj: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan's strongly-connected components, iterative (lint inputs
    are untrusted; no recursion-limit surprises)."""
    index_of: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = 0
    for start in sorted(adj):
        if start in index_of:
            continue
        work: list[tuple[str, Optional[str], Iterator[str]]] = [
            (start, None, iter(sorted(adj[start])))
        ]
        while work:
            node, parent, children = work[-1]
            if node not in index_of:
                index_of[node] = low[node] = counter
                counter += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            for child in children:
                if child not in index_of:
                    work.append((child, node, iter(sorted(adj[child]))))
                    advanced = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index_of[child])
            if advanced:
                continue
            if low[node] == index_of[node]:
                scc: list[str] = []
                while True:
                    top = stack.pop()
                    on_stack.discard(top)
                    scc.append(top)
                    if top == node:
                        break
                sccs.append(scc)
            work.pop()
            if parent is not None:
                low[parent] = min(low[parent], low[node])
    return sccs


ALL_RULES = ALL_RULES + (
    UnguardedSharedStateRule,
    LockOrderInversionRule,
    BlockingUnderLockRule,
)


def default_rules(config: Optional[LintConfig] = None) -> list[Rule]:
    config = config or LintConfig()
    return [
        HostDeviceSyncRule(config.hot_path_dirs),
        TracerBranchRule(),
        RecompilationHazardRule(),
        BlockingCallRule(config.hot_path_files),
        LockDisciplineRule(config.hot_path_files),
        ExceptionSwallowRule(config.request_path_dirs),
        DonatedBufferReuseRule(),
        ScanBodyAsarrayRule(),
        JitCacheGrowthRule(),
        RepeatedHostPullRule(),
        PerRowClockRule(config.hot_path_files),
        BlockingIONoTimeoutRule(),
        RetryNoBackoffRule(),
        CrossMeshHostPullRule(),
        JitInRequestPathRule(),
        UnboundedMetricLabelRule(),
        ThresholdNoHysteresisRule(),
        HostPullInDeviceLegRule(),
        SyncOutsideDeviceWaitRule(),
        AckBeforeResultRule(),
        HandleNoDeadlineRule(),
        DuplicatedLogitsPathRule(),
        UnguardedSharedStateRule(config.concurrency_dirs),
        LockOrderInversionRule(config.concurrency_dirs),
        BlockingUnderLockRule(config.concurrency_dirs),
    ]
